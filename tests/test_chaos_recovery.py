"""Two-worker recovery acceptance (ISSUE 12, default tier): the
cross-worker scorer failover ladder and the graceful handoff — the
in-process, deterministic versions of what `bench.py chaos_drill`
drives across real processes.

Harness mirrors tests/test_obs_cluster.py: two RoomFabric workers on
real sockets sharing one MemoryStore (the cluster's coordination
plane), each with its OWN supervisor and a breaker-aware similarity
bound to it — so one worker's score breaker can be dark while the
other stays healthy."""

import asyncio
import dataclasses
import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer  # noqa: F401

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.engine.content import FakeContentBackend, hash_embed
from cassmantle_tpu.engine.game import Game
from cassmantle_tpu.engine.store import MemoryStore
from cassmantle_tpu.fabric.rooms import RoomFabric, room_prefix

# the recognizable non-floor score the healthy similarity produces:
# floor-path scores clamp to min_score (0.01), so 0.5 in a response
# proves a REAL similarity computation ran (not the breaker's zeros)
REAL_SIM = 0.5


def make_cfg(num_rooms=8):
    cfg = _tiny_config()
    return cfg.replace(
        game=dataclasses.replace(
            cfg.game, time_per_prompt=60.0,
            rate_limit_default=1e6, rate_limit_api=1e6),
        fabric=dataclasses.replace(
            cfg.fabric, num_rooms=num_rooms, heartbeat_s=30.0,
            membership_ttl_s=120.0, handoff_grace_s=3.0),
    )


def breaker_similarity(sup):
    """The production InferenceService.similarity contract in
    miniature: an open score breaker floors instantly; healthy returns
    the recognizable REAL_SIM for every pair."""

    async def sim(pairs):
        pairs = list(pairs)
        if not sup.score_breaker.allow():
            return np.zeros((len(pairs),), dtype=np.float32)
        return np.full((len(pairs),), REAL_SIM, dtype=np.float32)

    return sim


async def _start_worker(cfg, store, worker_id):
    from cassmantle_tpu.server.app import create_app
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    sup = ServingSupervisor()

    def factory(room, room_store):
        return Game(cfg, room_store,
                    FakeContentBackend(image_size=16), hash_embed,
                    breaker_similarity(sup), supervisor=sup, room=room)

    fabric = RoomFabric(cfg, store, factory, worker_id=worker_id,
                        start_timers=False, heartbeat=True,
                        supervisor=sup)
    server = TestServer(create_app(fabric, cfg, start_timer=False))
    await server.start_server()
    url = f"http://127.0.0.1:{server.port}"
    fabric.membership.addr = url
    return server, fabric, url


async def _sync_membership(fabrics):
    for f in fabrics:
        await f.membership.heartbeat(len(f._games))
    for f in fabrics:
        live = await f.membership.refresh()
        await f._handle_moves(f._apply_membership(live))


def _trip(breaker):
    for _ in range(breaker.failure_threshold):
        breaker.record_failure()


async def _two_workers():
    cfg = make_cfg()
    store = MemoryStore()
    server_a, fabric_a, url_a = await _start_worker(cfg, store, "w-a")
    server_b, fabric_b, url_b = await _start_worker(cfg, store, "w-b")
    await _sync_membership([fabric_a, fabric_b])
    return (cfg, store, (server_a, fabric_a, url_a),
            (server_b, fabric_b, url_b))


async def _answer_for(store, cfg, room, mask):
    prefix = room_prefix(room, cfg.fabric.default_room)
    raw = await store.hget(prefix + "prompt", "current")
    prompt = json.loads(raw.decode())
    return prompt["tokens"][int(mask)]


@pytest.mark.asyncio
async def test_scorer_failover_hedges_to_peer_then_floors():
    """The ISSUE 12 failover acceptance: with w-a's score breaker
    forced open and w-b healthy, /compute_score on w-a answers REAL
    (non-floor) scores computed by the peer; with zero healthy peers
    it degrades to floor scores — both pinned end to end."""
    import aiohttp

    from cassmantle_tpu.utils.logging import metrics

    cfg, store, (server_a, fabric_a, url_a), \
        (server_b, fabric_b, url_b) = await _two_workers()
    http = aiohttp.ClientSession()
    try:
        room = next(r for r, w in fabric_a.directory.placement().items()
                    if w == "w-a")
        q = f"?room={room}&session=hedge-s"
        res = await http.get(url_a + "/fetch/contents" + q)
        assert res.status == 200
        mask = (await res.json())["prompt"]["masks"][0]

        _trip(fabric_a.supervisor.score_breaker)
        hedges_before = metrics.counter_total("score.hedge_success")
        res = await http.post(url_a + "/compute_score" + q,
                              json={"inputs": {str(mask): "wrong"}})
        assert res.status == 200
        assert res.headers.get("X-Score-Hedged") == "1"
        scores = await res.json()
        # REAL similarity (0.5), not the floor (min_score): the peer's
        # healthy scorer computed this, w-a's dark one never could
        assert float(scores[str(mask)]) == pytest.approx(REAL_SIM)
        assert metrics.counter_total("score.hedge_success") \
            == hedges_before + 1

        # zero healthy peers: w-b's breaker dark too -> its hedge leg
        # sheds 503 and w-a bottoms out at marked floor scores
        _trip(fabric_b.supervisor.score_breaker)
        res = await http.post(url_a + "/compute_score" + q,
                              json={"inputs": {str(mask): "wrong2"}})
        assert res.status == 200
        assert res.headers.get("X-Score-Degraded") == "floor"
        assert "X-Score-Hedged" not in res.headers
        scores = await res.json()
        assert float(scores[str(mask)]) == pytest.approx(
            cfg.game.min_score)

        # recovery: both breakers close, scores are local + real again
        fabric_a.supervisor.score_breaker.record_success()
        fabric_b.supervisor.score_breaker.record_success()
        res = await http.post(url_a + "/compute_score" + q,
                              json={"inputs": {str(mask): "wrong3"}})
        assert res.status == 200
        assert "X-Score-Hedged" not in res.headers
        assert "X-Score-Degraded" not in res.headers
        assert float((await res.json())[str(mask)]) \
            == pytest.approx(REAL_SIM)
    finally:
        await http.close()
        await server_a.close()
        await server_b.close()


@pytest.mark.asyncio
async def test_exact_guess_wins_through_the_hedge():
    """A correct guess scored THROUGH the hedge persists to the shared
    store: the session's win state is visible from either worker
    (the peer's writes are the same store rows w-a would have
    written)."""
    import aiohttp

    cfg, store, (server_a, fabric_a, url_a), \
        (server_b, fabric_b, url_b) = await _two_workers()
    http = aiohttp.ClientSession()
    try:
        room = next(r for r, w in fabric_a.directory.placement().items()
                    if w == "w-a")
        q = f"?room={room}&session=hedge-win"
        res = await http.get(url_a + "/fetch/contents" + q)
        prompt = (await res.json())["prompt"]
        masks = prompt["masks"]
        answers = {str(m): await _answer_for(store, cfg, room, m)
                   for m in masks}

        _trip(fabric_a.supervisor.score_breaker)
        res = await http.post(url_a + "/compute_score" + q,
                              json={"inputs": answers})
        assert res.status == 200
        assert res.headers.get("X-Score-Hedged") == "1"
        body = await res.json()
        assert body["won"] == 1
        # the win is in the shared store, not a peer-local artifact
        res = await http.get(url_a + "/client/status" + q)
        assert (await res.json())["won"] == 1
    finally:
        await http.close()
        await server_a.close()
        await server_b.close()


@pytest.mark.asyncio
async def test_graceful_handoff_adopts_rooms_before_exit():
    """The ISSUE 12 handoff acceptance, deterministic in-process: w-a
    hands off; w-b's next heartbeat adopts w-a's rooms while w-a is
    still alive (the handoff returns only after observing that beat);
    a score accepted on w-a before the handoff is served by w-b after
    — no lost accepted scores."""
    import aiohttp

    from cassmantle_tpu.obs import flight_recorder

    cfg, store, (server_a, fabric_a, url_a), \
        (server_b, fabric_b, url_b) = await _two_workers()
    http = aiohttp.ClientSession()
    try:
        a_rooms = fabric_a.owned_rooms()
        room = a_rooms[0]
        q = f"?room={room}&session=handoff-s"
        res = await http.get(url_a + "/fetch/contents" + q)
        mask = (await res.json())["prompt"]["masks"][0]
        res = await http.post(url_a + "/compute_score" + q,
                              json={"inputs": {str(mask): "keepme"}})
        assert res.status == 200
        score_before = (await res.json())[str(mask)]

        async def beat_b():
            # w-b's heartbeat loop is parked at 30s in this harness:
            # beat it manually once the handoff is waiting, exactly
            # what the live loop does every heartbeat_s
            await asyncio.sleep(0.15)
            await fabric_b.membership.heartbeat(len(fabric_b._games))
            live = await fabric_b.membership.refresh()
            await fabric_b._handle_moves(
                fabric_b._apply_membership(live))

        beat = asyncio.ensure_future(beat_b())
        await fabric_a.handoff()
        await beat
        # adoption happened BEFORE handoff returned (w-a still alive):
        # w-b owns every ex-w-a room on ITS ring, and w-a's ring
        # agrees (requests w-a still answers would 307 to w-b)
        assert fabric_a.draining
        for r in a_rooms:
            assert fabric_b.directory.worker_for_room(r) == "w-b"
            assert fabric_a.directory.worker_for_room(r) == "w-b"
        assert fabric_a._games == {}
        kinds = [e["kind"] for e in flight_recorder.tail(50)]
        assert "fabric.handoff_started" in kinds
        assert "fabric.handoff_complete" in kinds

        # w-a still answers probes while draining: /readyz says so
        res = await http.get(url_a + "/readyz")
        assert res.status == 503
        assert (await res.json())["state"] == "draining"

        # no lost accepted scores: w-b serves the same session state
        res = await http.get(url_b + "/fetch/contents" + q)
        assert res.status == 200
        after = (await res.json())["prompt"]["scores"]
        assert float(after[str(mask)]) == pytest.approx(
            float(score_before))
        res = await http.get(url_b + "/client/status" + q)
        assert (await res.json())["needInitialization"] is False
    finally:
        await http.close()
        await server_a.close()
        await server_b.close()


@pytest.mark.asyncio
async def test_handoff_without_peers_exits_promptly():
    """A solo worker's handoff must not burn the grace window waiting
    for peers that do not exist (fleet-wide shutdown shape)."""
    cfg = make_cfg(num_rooms=2)
    store = MemoryStore()
    server, fabric, _ = await _start_worker(cfg, store, "w-solo")
    try:
        await _sync_membership([fabric])
        t0 = asyncio.get_running_loop().time()
        await fabric.handoff()
        assert asyncio.get_running_loop().time() - t0 < 1.0
        assert fabric.draining
    finally:
        await server.close()
