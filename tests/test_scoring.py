import numpy as np
import pytest

from cassmantle_tpu.engine.content import hash_similarity
from cassmantle_tpu.engine.scoring import GuessScorer, score_to_blur


@pytest.mark.asyncio
async def test_exact_match_scores_one():
    scorer = GuessScorer(hash_similarity, min_score=0.01)
    scores = await scorer.score_pairs(
        {"3": {"input": "Lighthouse", "answer": "lighthouse"}}
    )
    assert scores["3"] == 1.0


@pytest.mark.asyncio
async def test_mismatch_floored_and_below_one():
    scorer = GuessScorer(hash_similarity, min_score=0.01)
    scores = await scorer.score_pairs(
        {"3": {"input": "boat", "answer": "lighthouse"},
         "7": {"input": "tower", "answer": "lighthouse"}}
    )
    for v in scores.values():
        assert 0.01 <= v < 1.0


@pytest.mark.asyncio
async def test_batched_call_single_similarity_invocation():
    calls = []

    async def spy_similarity(pairs):
        calls.append(len(pairs))
        return np.zeros(len(pairs), dtype=np.float32)

    scorer = GuessScorer(spy_similarity, min_score=0.05)
    scores = await scorer.score_pairs(
        {str(i): {"input": f"w{i}", "answer": "target"} for i in range(10)}
    )
    assert calls == [10]
    assert all(v == 0.05 for v in scores.values())


def test_score_to_blur_curve():
    assert score_to_blur(1.0) == 0.0
    assert score_to_blur(0.0) == 15.0
    mid = score_to_blur(0.5)
    assert mid == pytest.approx(15.0 * 0.75)
    # monotone decreasing
    xs = np.linspace(0, 1, 11)
    blurs = [score_to_blur(x) for x in xs]
    assert all(b1 >= b2 for b1, b2 in zip(blurs, blurs[1:]))
