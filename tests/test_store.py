import asyncio

import pytest

from cassmantle_tpu.engine.store import LockTimeout, MemoryStore


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def store(clock):
    return MemoryStore(clock=clock)


def run(coro):
    return asyncio.get_event_loop().run_until_complete(coro)


@pytest.mark.asyncio
async def test_plain_keys_and_ttl(store, clock):
    await store.setex("countdown", 10.0, "active")
    assert await store.exists("countdown")
    assert await store.ttl("countdown") == pytest.approx(10.0)
    clock.t = 5.0
    assert await store.ttl("countdown") == pytest.approx(5.0)
    clock.t = 10.0
    assert not await store.exists("countdown")
    assert await store.ttl("countdown") == -2.0


@pytest.mark.asyncio
async def test_ttl_semantics_no_expiry(store):
    await store.set("k", "v")
    assert await store.ttl("k") == -1.0
    assert await store.get("k") == b"v"


@pytest.mark.asyncio
async def test_hash_ops(store):
    await store.hset("session", mapping={"max": 0.01, "won": 0, "attempts": 0})
    await store.hset("session", "3", "0.5")
    assert await store.hget("session", "max") == b"0.01"
    all_ = await store.hgetall("session")
    assert set(all_) == {"max", "won", "attempts", "3"}
    assert await store.hincrby("session", "attempts") == 1
    assert await store.hincrby("session", "attempts", 2) == 3
    await store.hdel("session", "3")
    assert await store.hget("session", "3") is None


@pytest.mark.asyncio
async def test_hash_expiry(store, clock):
    await store.hset("session", "won", 0)
    await store.expire("session", 2.0)
    clock.t = 3.0
    assert await store.hgetall("session") == {}


@pytest.mark.asyncio
async def test_set_ops(store):
    await store.sadd("sessions", "a", "b")
    assert await store.sismember("sessions", "a")
    assert not await store.sismember("sessions", "c")
    await store.srem("sessions", "a")
    assert await store.smembers("sessions") == {"b"}


@pytest.mark.asyncio
async def test_lock_mutual_exclusion(store):
    order = []

    async def holder():
        async with store.lock("l", timeout=5.0, blocking_timeout=1.0):
            order.append("h-in")
            await asyncio.sleep(0.1)
            order.append("h-out")

    async def waiter():
        await asyncio.sleep(0.01)
        async with store.lock("l", timeout=5.0, blocking_timeout=1.0):
            order.append("w-in")

    await asyncio.gather(holder(), waiter())
    assert order == ["h-in", "h-out", "w-in"]


@pytest.mark.asyncio
async def test_lock_acquire_timeout():
    store = MemoryStore()  # real clock: blocking_timeout is wall time
    async def holder():
        async with store.lock("l", timeout=5.0, blocking_timeout=0.5):
            await asyncio.sleep(0.3)

    async def contender():
        await asyncio.sleep(0.01)
        with pytest.raises(LockTimeout):
            async with store.lock("l", timeout=5.0, blocking_timeout=0.05):
                pass

    await asyncio.gather(holder(), contender())


@pytest.mark.asyncio
async def test_lock_hold_timeout_self_expires(store, clock):
    """A crashed holder's lock must self-expire (redis-TTL semantics)."""
    mgr = store.lock("l", timeout=2.0, blocking_timeout=0.1)
    await mgr.__aenter__()  # never exited: simulated crash
    clock.t = 3.0
    async with store.lock("l", timeout=2.0, blocking_timeout=0.1):
        pass  # acquired because the stale lock expired


@pytest.mark.asyncio
async def test_snapshot_restore(tmp_path, store, clock):
    await store.hset("prompt", "current", '{"tokens": []}')
    await store.setex("countdown", 10.0, "active")
    await store.sadd("sessions", "s1")
    clock.t = 4.0
    path = str(tmp_path / "snap.pkl")
    store.snapshot(path)

    clock2 = FakeClock()
    clock2.t = 100.0
    store2 = MemoryStore(clock=clock2)
    store2.restore(path)
    assert await store2.hget("prompt", "current") == b'{"tokens": []}'
    assert await store2.ttl("countdown") == pytest.approx(6.0)
    assert await store2.smembers("sessions") == {"s1"}


@pytest.mark.asyncio
async def test_lock_overrun_detected(store, clock):
    """Race DETECTION (SURVEY §5.2 upgrade over the reference's silent
    window): a hold that outlives its TTL is counted and logged —
    'overrun' when still unclaimed, 'expired_in_hold' when another
    worker took it meanwhile."""
    from cassmantle_tpu.utils.logging import metrics

    before = metrics.snapshot()["counters"].get("store.lock_overrun", 0)
    async with store.lock("l", timeout=2.0, blocking_timeout=0.1):
        clock.t = 5.0   # critical section ran past the TTL
    after = metrics.snapshot()["counters"].get("store.lock_overrun", 0)
    assert after == before + 1

    before = metrics.snapshot()["counters"].get(
        "store.lock_expired_in_hold", 0)
    async with store.lock("l2", timeout=2.0, blocking_timeout=0.1):
        clock.t += 5.0  # expire...
        async with store.lock("l2", timeout=2.0, blocking_timeout=0.1):
            pass        # ...reacquired and released live by "another
            # worker", so the outer release finds its token gone
    after = metrics.snapshot()["counters"].get(
        "store.lock_expired_in_hold", 0)
    assert after == before + 1
