"""Bench regression sentinel (tools/bench_diff.py, ISSUE 14): verdict
grammar (regression / improvement / within-noise / missing / error /
skipped / new), per-entry noise tolerances, diagnosis counter-delta
surfacing, direction-by-unit, and the CLI exit-code acceptance
contract. Fast tier; stdlib-only module, no jax."""

import json
import os
import subprocess
import sys

import pytest

from tools.bench_diff import (
    DEFAULT_TOLERANCE,
    diff_entry,
    diff_suites,
    format_table,
    higher_is_better,
    main,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _entry(value, unit="images/sec", **extra):
    return {"metric": "m", "value": value, "unit": unit, **extra}


# -- verdict grammar --------------------------------------------------------

def test_regression_beyond_tolerance_flags():
    row = diff_entry("sd15", _entry(1.0), _entry(0.8))
    assert row["verdict"] == "regression"
    assert row["change_pct"] == pytest.approx(-20.0)


def test_improvement_beyond_tolerance():
    row = diff_entry("sd15", _entry(1.0), _entry(1.3))
    assert row["verdict"] == "improvement"


def test_within_noise_band():
    assert diff_entry("sd15", _entry(1.0),
                      _entry(1.05))["verdict"] == "within_noise"
    assert diff_entry("sd15", _entry(1.0),
                      _entry(0.95))["verdict"] == "within_noise"


def test_missing_entry_flags():
    row = diff_entry("sd15", _entry(1.0), None)
    assert row["verdict"] == "missing"


def test_fresh_error_over_measured_baseline_flags():
    row = diff_entry("sd15", _entry(1.0), {"error": "tunnel died"})
    assert row["verdict"] == "error"
    assert "tunnel died" in row["error"]


def test_pending_hardware_baseline_skipped():
    """The pending-hardware annotations (gpt2_spec & co) are baseline
    entries with an error field: nothing to regress against — both on
    an identical fresh copy and when the fresh run also errors."""
    pending = {"metric": "m", "error": "pending hardware window"}
    assert diff_entry("gpt2_spec", pending,
                      pending)["verdict"] == "skipped"
    assert diff_entry("gpt2_spec", pending, None)["verdict"] == "skipped"


def test_new_entry_is_informational():
    assert diff_entry("fresh_only", None, _entry(2.0))["verdict"] == "new"


# -- direction by unit ------------------------------------------------------

def test_seconds_units_are_lower_better():
    assert not higher_is_better({"unit": "seconds"})
    assert higher_is_better({"unit": "tokens/sec"})
    assert higher_is_better({"unit": "accepted req/s"})
    # latency REGRESSION = value going UP
    row = diff_entry("e2e", _entry(1.0, unit="seconds"),
                     _entry(1.4, unit="seconds"))
    assert row["verdict"] == "regression"
    row = diff_entry("e2e", _entry(1.0, unit="seconds"),
                     _entry(0.7, unit="seconds"))
    assert row["verdict"] == "improvement"


# -- tolerances carried per entry -------------------------------------------

def test_per_entry_tolerance_overrides_default():
    base = _entry(1.0, noise_tolerance=0.3)
    assert diff_entry("noisy", base, _entry(0.75))["verdict"] \
        == "within_noise"
    # the fresh record's tolerance wins over the baseline's
    row = diff_entry("noisy", base, _entry(0.75, noise_tolerance=0.05))
    assert row["verdict"] == "regression"
    assert diff_entry("tight", _entry(1.0),
                      _entry(0.8))["verdict"] == "regression"
    assert DEFAULT_TOLERANCE == pytest.approx(0.10)


# -- diagnosis counter deltas -----------------------------------------------

def test_regression_surfaces_counter_delta_changes():
    base = _entry(1.0, counter_deltas={"jit.compiles": 40})
    fresh = _entry(0.7, counter_deltas={"jit.compiles": 40,
                                        "jit.recompiles": 900})
    row = diff_entry("sd15", base, fresh)
    assert row["verdict"] == "regression"
    changes = row["counter_delta_changes"]
    assert changes == {"jit.recompiles": {"baseline": None,
                                          "fresh": 900}}
    table = format_table([row])
    assert "jit.recompiles" in table and "900" in table


def test_within_noise_carries_no_diagnosis():
    base = _entry(1.0, counter_deltas={"jit.compiles": 40})
    fresh = _entry(0.99, counter_deltas={"jit.compiles": 41})
    assert "counter_delta_changes" not in diff_entry("sd15", base, fresh)


# -- suite-level diff -------------------------------------------------------

def test_diff_suites_covers_union_and_restriction():
    base = {"a": _entry(1.0), "b": _entry(2.0)}
    fresh = {"a": _entry(1.0), "c": _entry(3.0)}
    rows = {r["entry"]: r["verdict"] for r in diff_suites(base, fresh)}
    assert rows == {"a": "within_noise", "b": "missing", "c": "new"}
    only = diff_suites(base, fresh, entries=["a"])
    assert [r["entry"] for r in only] == ["a"]


# -- CLI acceptance contract ------------------------------------------------

def _write(tmp_path, name, data):
    path = tmp_path / name
    path.write_text(json.dumps(data))
    return str(path)


def test_cli_unmodified_committed_suite_exits_zero(capsys):
    """The acceptance bar: bench_diff against an unmodified copy of the
    committed BENCH_SUITE.json exits 0."""
    assert main([os.path.join(REPO, "BENCH_SUITE.json")]) == 0
    out = capsys.readouterr().out
    assert "within_noise" in out


def test_cli_degraded_entry_exits_nonzero_naming_it(tmp_path, capsys):
    """...and against a copy with one entry's throughput degraded 20%
    exits nonzero NAMING that entry."""
    with open(os.path.join(REPO, "BENCH_SUITE.json")) as f:
        suite = json.load(f)
    suite["sd15"]["value"] = round(suite["sd15"]["value"] * 0.8, 4)
    fresh = _write(tmp_path, "degraded.json", suite)
    assert main([fresh]) == 1
    captured = capsys.readouterr()
    assert "sd15" in captured.err and "regression" in captured.err


def test_cli_entry_mode_accepts_records_with_dict_fields(tmp_path,
                                                         capsys):
    """A real bench.py --entry record carries dict-valued fields
    (counter_deltas — the diagnosis data this tool exists for); the
    single-record detection must not misread it as a suite mapping
    (which would verdict every healthy run 'missing')."""
    base = _write(tmp_path, "base.json", {"sd15": _entry(1.0)})
    single = _write(tmp_path, "single.json",
                    _entry(1.0, counter_deltas={"jit.compiles": 12},
                           cpu_smoke={"value": 0.5}))
    assert main([single, "--baseline", base, "--entry", "sd15"]) == 0
    assert "within_noise" in capsys.readouterr().out


def test_cli_entry_mode_places_single_record(tmp_path, capsys):
    base = _write(tmp_path, "base.json",
                  {"sd15": _entry(1.0), "gpt2": _entry(500.0,
                                                       unit="tokens/sec")})
    single = _write(tmp_path, "single.json", _entry(0.5))
    rc = main([single, "--baseline", base, "--entry", "sd15"])
    assert rc == 1
    assert "sd15" in capsys.readouterr().err
    # a single record without --entry is a usage error
    with pytest.raises(SystemExit):
        main([single, "--baseline", base])
    # --entry restriction: the OTHER entries are not "missing"
    ok = _write(tmp_path, "ok.json", _entry(1.0))
    assert main([ok, "--baseline", base, "--entry", "sd15"]) == 0


def test_cli_json_output(tmp_path, capsys):
    base = _write(tmp_path, "base.json", {"a": _entry(1.0)})
    fresh = _write(tmp_path, "fresh.json", {"a": _entry(1.0)})
    assert main([fresh, "--baseline", base, "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert rows[0]["verdict"] == "within_noise"


def test_cli_subprocess_against_committed_suite():
    """The exact invocation the acceptance criteria name, as a child
    process (exit code is the contract CI keys on)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_diff.py"),
         os.path.join(REPO, "BENCH_SUITE.json")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
