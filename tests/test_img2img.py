"""img2img path: VAE encoder, DDIM-tail sampling, converter round trip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.serving.pipeline import Text2ImagePipeline


@pytest.fixture(scope="module")
def pipe():
    return Text2ImagePipeline(_tiny_config())


def _img(seed, size):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 255, (1, size, size, 3), dtype=np.uint8)


def test_img2img_shapes_and_determinism(pipe):
    size = pipe.cfg.sampler.image_size
    img = _img(0, size)
    out1 = pipe.generate_img2img(img, ["a stormy sea"], strength=0.5,
                                 seed=3)
    out2 = pipe.generate_img2img(img, ["a stormy sea"], strength=0.5,
                                 seed=3)
    assert out1.shape == (1, size, size, 3) and out1.dtype == np.uint8
    np.testing.assert_array_equal(out1, out2)


def test_img2img_strength_bounds(pipe):
    size = pipe.cfg.sampler.image_size
    img = _img(1, size)
    with pytest.raises(AssertionError):
        pipe.generate_img2img(img, ["x"], strength=0.0)
    with pytest.raises(AssertionError):
        pipe.generate_img2img(img, ["x"], strength=1.5)
    # strength=1.0 runs the full schedule (pure generation budget)
    out = pipe.generate_img2img(img, ["a quiet harbor"], strength=1.0)
    assert out.shape == (1, size, size, 3)


def test_img2img_low_strength_stays_closer_to_input(pipe):
    """Lower strength -> output keeps more of the input than higher
    strength. Measured against the VAE ROUND-TRIP of the input
    (decode(encode(img)), same encoder rng the pipeline derives from
    the seed) — that reconstruction is the anchor the schedule
    actually preserves. Comparing against the RAW input was a coin
    flip with the tiny random-init VAE (reconstruction error swamps
    the anchoring; it flipped 82.05 vs 81.85 on the schema-v3 init
    draw), while the round-trip anchor separates under any draw."""
    import jax
    import jax.numpy as jnp

    from cassmantle_tpu.models.vae import postprocess_images

    size = pipe.cfg.sampler.image_size
    img = _img(2, size)
    seed = 5
    pipe._ensure_encoder()
    rng_enc, _ = jax.random.split(jax.random.PRNGKey(seed))
    imgf = jnp.asarray(img.astype(np.float32) / 127.5 - 1.0)
    lat0 = pipe.vae_enc.apply(pipe.enc_params, imgf, rng_enc)
    base = np.asarray(
        postprocess_images(pipe.vae.apply(pipe._params["vae"], lat0)),
        dtype=np.float32)

    lo = pipe.generate_img2img(img, ["the same scene"], strength=0.1,
                               seed=seed)
    hi = pipe.generate_img2img(img, ["the same scene"], strength=1.0,
                               seed=seed)
    d_lo = np.abs(lo.astype(np.float32) - base).mean()
    d_hi = np.abs(hi.astype(np.float32) - base).mean()
    assert d_lo < d_hi, (d_lo, d_hi)


@pytest.mark.parametrize("kind", ("euler", "dpmpp_2m"))
def test_img2img_respects_sampler_kind(kind):
    """img2img runs under the configured solver (not silently DDIM) and
    low strength still tracks the input for every kind."""
    import dataclasses

    base = _tiny_config()
    cfg = base.replace(sampler=dataclasses.replace(base.sampler, kind=kind))
    p = Text2ImagePipeline(cfg)
    size = cfg.sampler.image_size
    img = _img(7, size)
    lo = p.generate_img2img(img, ["same scene"], strength=0.25, seed=1)
    hi = p.generate_img2img(img, ["same scene"], strength=1.0, seed=1)
    assert lo.shape == (1, size, size, 3)
    base_f = img.astype(np.float32)
    assert np.abs(lo.astype(np.float32) - base_f).mean() < \
        np.abs(hi.astype(np.float32) - base_f).mean()


def test_vae_encoder_latents_shape(pipe):
    pipe._ensure_encoder()
    size = pipe.cfg.sampler.image_size
    img = jnp.zeros((2, size, size, 3), jnp.float32)
    lat = pipe.vae_enc.apply(pipe.enc_params, img, jax.random.PRNGKey(0))
    assert lat.shape == (2, size // pipe.vae_scale,
                         size // pipe.vae_scale, 4)
    assert np.isfinite(np.asarray(lat)).all()


def test_convert_vae_encoder_roundtrip(pipe):
    """Fabricate a diffusers-layout encoder checkpoint from known params
    and assert exact reproduction (mirrors the decoder converter test)."""
    from cassmantle_tpu.models.weights import convert_vae_encoder

    pipe._ensure_encoder()
    cfg = pipe.cfg.models.vae
    p = pipe.enc_params["params"]
    src = {}

    def put_conv(key, tree):
        src[f"{key}.weight"] = np.transpose(
            np.asarray(tree["kernel"]), (3, 2, 0, 1))
        if "bias" in tree:
            src[f"{key}.bias"] = np.asarray(tree["bias"])

    def put_gn(key, tree):
        src[f"{key}.weight"] = np.asarray(tree["norm"]["scale"])
        src[f"{key}.bias"] = np.asarray(tree["norm"]["bias"])

    def put_res(key, tree):
        put_gn(f"{key}.norm1", tree["norm1"])
        put_conv(f"{key}.conv1", tree["conv1"])
        put_gn(f"{key}.norm2", tree["norm2"])
        put_conv(f"{key}.conv2", tree["conv2"])
        if "skip" in tree:
            put_conv(f"{key}.conv_shortcut", tree["skip"])

    def put_dense(key, tree):
        src[f"{key}.weight"] = np.asarray(tree["kernel"]).T
        if "bias" in tree:
            src[f"{key}.bias"] = np.asarray(tree["bias"])

    put_conv("quant_conv", p["quant_conv"])
    put_conv("encoder.conv_in", p["conv_in"])
    levels = len(cfg.channel_mults)
    for lvl in range(levels):
        for blk in range(cfg.blocks_per_level):
            put_res(f"encoder.down_blocks.{lvl}.resnets.{blk}",
                    p[f"down_{lvl}_res_{blk}"])
        if lvl != levels - 1:
            put_conv(f"encoder.down_blocks.{lvl}.downsamplers.0.conv",
                     p[f"down_{lvl}_downsample"])
    put_res("encoder.mid_block.resnets.0", p["mid_res_0"])
    attn = p["mid_attn"]
    put_gn("encoder.mid_block.attentions.0.group_norm", attn["norm"])
    for ours, theirs in (("q", "to_q"), ("k", "to_k"), ("v", "to_v"),
                         ("out", "to_out.0")):
        put_dense(f"encoder.mid_block.attentions.0.{theirs}",
                  attn["attn"][ours])
    put_res("encoder.mid_block.resnets.1", p["mid_res_1"])
    put_gn("encoder.conv_norm_out", p["norm_out"])
    put_conv("encoder.conv_out", p["conv_out"])

    converted = convert_vae_encoder(src, cfg)
    flat_a = {jax.tree_util.keystr(k): v for k, v in
              jax.tree_util.tree_leaves_with_path(pipe.enc_params)}
    flat_b = {jax.tree_util.keystr(k): v for k, v in
              jax.tree_util.tree_leaves_with_path(converted)}
    assert flat_a.keys() == flat_b.keys()
    for key, val in flat_a.items():
        np.testing.assert_array_equal(np.asarray(val),
                                      np.asarray(flat_b[key]), err_msg=key)
