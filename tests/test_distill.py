"""Consistency/LCM distillation of the zoo UNet (ISSUE 15).

The contract that makes the few-step student servable:
1. the skip-step consistency loss DECREASES on toy geometry — the
   distillation objective is trainable end to end on the existing
   train infrastructure (parallel/train.py);
2. the EMA target network update is exactly d·ema + (1−d)·student,
   inside the jitted step;
3. the student shares the teacher's checkpoint layout (identical param
   pytree — structure, shapes, dtypes), so utils/checkpoint.py and the
   serving weights path (share_compatible, maybe_load) work unchanged;
4. a toy student distilled IN-PROCESS generates through the REAL
   pipeline with ≤ 8 UNet forwards per image, counter-verified
   (`pipeline.consistency_steps` — the acceptance bar);
5. the brownout ladder's few-step tier sits BEFORE the resolution
   tier, engages through the pipeline (full resolution, 4 forwards),
   and its variant compiles once (jit-sentinel pinned).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.models.unet import UNet
from cassmantle_tpu.models.weights import init_params
from cassmantle_tpu.parallel.train import ConsistencyDistillTrainer


def _teacher_params(cfg):
    unet = UNet(cfg.models.unet)
    lat = jnp.zeros((2, 8, 8, 4))
    t = jnp.zeros((2,), jnp.int32)
    ctx = jnp.zeros((2, 6, cfg.models.unet.context_dim))
    return init_params(unet, 0, lat, t, ctx)


def _toy_batch(cfg, b=2, hw=8, seq=6, seed=1):
    d = cfg.models.unet.context_dim
    return {
        "latents": jax.random.normal(jax.random.PRNGKey(seed),
                                     (b, hw, hw, 4)),
        "context": jax.random.normal(jax.random.PRNGKey(seed + 1),
                                     (b, seq, d)),
    }


@pytest.fixture(scope="module")
def cfg():
    return _tiny_config()


@pytest.fixture(scope="module")
def teacher(cfg):
    return _teacher_params(cfg)


@pytest.fixture(scope="module")
def trainer(cfg):
    """ONE trainer (one jitted distill step) shared by the loss, EMA,
    layout, and acceptance tests — the UNet fwd+bwd compile is the
    module's wall-clock cost and every test here uses the same toy
    geometry."""
    return ConsistencyDistillTrainer(cfg, mesh=None, lr=3e-3,
                                     solver_steps=8, skip=2,
                                     ema_decay=0.9, max_serve_steps=4)


# -- 1. the loss decreases ----------------------------------------------------


def test_distill_loss_decreases_on_toy_geometry(cfg, teacher, trainer):
    """Fixed batch + fixed rng = a deterministic objective; a handful
    of optimizer steps must reduce it. Losses are collected as device
    scalars and transferred ONCE (the collect-once shape the host-sync
    lint pins, tests/test_check_jax.py)."""
    student, ema, opt = trainer.init_state(teacher)
    batch = _toy_batch(cfg)
    rng = jax.random.PRNGKey(3)
    losses = []
    for _ in range(8):
        student, ema, opt, loss = trainer.step(
            student, ema, opt, teacher, batch, rng)
        losses.append(loss)
    curve = np.asarray(jnp.stack(losses))
    assert np.isfinite(curve).all()
    assert curve[-1] < curve[0], f"loss did not decrease: {curve}"


def test_skip_step_bounds_validated(cfg):
    with pytest.raises(AssertionError, match="skip"):
        ConsistencyDistillTrainer(cfg, solver_steps=8, skip=8)
    with pytest.raises(AssertionError, match="skip"):
        ConsistencyDistillTrainer(cfg, solver_steps=8, skip=0)
    # serving-coverage contract: a skip that narrows the trained range
    # below what a max_serve_steps schedule would query is rejected at
    # train time (the student would be served untrained noise levels)
    with pytest.raises(AssertionError, match="uncovered"):
        ConsistencyDistillTrainer(cfg, solver_steps=8, skip=2,
                                  max_serve_steps=8)


# -- 2. EMA target update math ------------------------------------------------


def test_ema_target_update_math(cfg, teacher, trainer):
    d = trainer.ema_decay
    student, ema, opt = trainer.init_state(teacher)
    # the step donates its state buffers: snapshot the EMA on host first
    ema_before = jax.device_get(ema)
    new_student, new_ema, _, _ = trainer.step(
        student, ema, opt, teacher, _toy_batch(cfg), jax.random.PRNGKey(0))
    expect = jax.tree_util.tree_map(
        lambda e, s: d * e + (1.0 - d) * np.asarray(s),
        ema_before, jax.device_get(new_student))
    flat_got = jax.tree_util.tree_leaves(jax.device_get(new_ema))
    flat_want = jax.tree_util.tree_leaves(expect)
    assert len(flat_got) == len(flat_want)
    for got, want in zip(flat_got, flat_want):
        np.testing.assert_allclose(got, want, atol=1e-6, rtol=1e-6)


# -- 3. checkpoint-layout compatibility with the teacher tree -----------------


def test_student_tree_matches_teacher_layout(cfg, teacher, trainer):
    """Identical pytree structure, shapes, and dtypes — the property
    that lets a distilled checkpoint flow through utils/checkpoint.py,
    convert/maybe_load, and ``share_compatible`` unchanged (the student
    IS a zoo UNet checkpoint)."""
    student, ema, _ = trainer.init_state(teacher)
    for tree in (student, ema):
        assert jax.tree_util.tree_structure(tree) == \
            jax.tree_util.tree_structure(teacher)
        for a, b in zip(jax.tree_util.tree_leaves(tree),
                        jax.tree_util.tree_leaves(teacher)):
            assert a.shape == b.shape and a.dtype == b.dtype
    # the donated buffers must not alias the frozen teacher's
    sa = jax.tree_util.tree_leaves(student)[0]
    ta = jax.tree_util.tree_leaves(teacher)[0]
    assert sa is not ta


# -- 4. the acceptance bar: few-step serving through the real pipeline --------


def test_toy_student_serves_few_step_through_real_pipeline(
        cfg, teacher, trainer):
    """Distill in-process at toy geometry, drop the student tree into
    the REAL Text2ImagePipeline under the lcm-style config, and verify
    ≤ 8 UNet forwards per image end-to-end via the
    `pipeline.consistency_steps` counter (the ISSUE 15 acceptance
    criterion). The swap itself is the checkpoint-layout property:
    the student tree IS a valid zoo UNet tree."""
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline
    from cassmantle_tpu.utils.logging import metrics

    student, ema, opt = trainer.init_state(teacher)
    batch = _toy_batch(cfg)
    rng = jax.random.PRNGKey(3)
    for _ in range(4):
        student, ema, opt, _ = trainer.step(
            student, ema, opt, teacher, batch, rng)

    # serve on the SAME solver discretization the trainer distilled on
    # (ops/samplers.py::ConsistencySchedule queries a subset of that
    # grid — the student is never evaluated at an untrained noise level)
    lcm_cfg = cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, consistency=True, num_steps=4,
        consistency_teacher_steps=trainer.solver_steps))
    pipe = Text2ImagePipeline(lcm_cfg)
    # serve the EMA student (the consistency-models serving convention)
    pipe._params = dict(pipe._params, unet=ema)
    before = metrics.counter_total("pipeline.consistency_steps")
    imgs = pipe.generate(["a quiet harbor at dawn",
                          "a stormy night at sea"], seed=5)
    forwards_per_image = (
        metrics.counter_total("pipeline.consistency_steps") - before
    ) / imgs.shape[0]
    assert imgs.dtype == np.uint8 and imgs.shape[0] == 2
    assert np.isfinite(imgs.astype(np.float32)).all()
    assert forwards_per_image == lcm_cfg.sampler.num_steps
    assert forwards_per_image <= 8


# -- 5. the brownout few-step tier --------------------------------------------


def test_few_step_tier_ordered_before_resolution_tier():
    from cassmantle_tpu.serving.overload import DEFAULT_TIERS

    consistency_at = min(i for i, t in enumerate(DEFAULT_TIERS)
                         if t.consistency)
    lowres_at = min(i for i, t in enumerate(DEFAULT_TIERS)
                    if t.image_size_scale < 1.0)
    assert consistency_at < lowres_at
    # severity invariant: once engaged, consistency stays engaged on
    # every later rung (stepping up only ever removes compute)
    assert all(t.consistency for t in DEFAULT_TIERS[consistency_at:])


def test_few_step_tier_engages_full_res_and_compiles_once(
        cfg, monkeypatch):
    """The few-step tier through the real pipeline: full resolution
    (the resolution tier has NOT engaged yet), 4 consistency forwards
    counter-verified, and the tier variant compiles ONCE — the second
    degraded generate runs under the jit sentinel's zero-new-compiles
    pin."""
    monkeypatch.delenv("CASSMANTLE_NO_BROWNOUT", raising=False)
    monkeypatch.delenv("CASSMANTLE_NO_CONSISTENCY", raising=False)
    from cassmantle_tpu.serving import overload
    from cassmantle_tpu.serving.overload import (
        BrownoutLadder,
        CONSISTENCY_BROWNOUT_STEPS,
        DEFAULT_TIERS,
    )
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline
    from cassmantle_tpu.utils import jit_sentinel
    from cassmantle_tpu.utils.logging import metrics

    # a deployment that DECLARES its checkpoint distilled — the gate
    # that lets the few-step tier engage on a teacher-serving config
    # (without consistency_available the rung degrades steps only)
    pipe = Text2ImagePipeline(cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, consistency_available=True)))
    full = pipe.generate(["a storm rolls in"], seed=1)
    ladder = BrownoutLadder(DEFAULT_TIERS)
    monkeypatch.setattr(overload, "_LADDER", ladder)
    tier = min(i for i, t in enumerate(DEFAULT_TIERS) if t.consistency)
    with ladder._lock:
        ladder._step_to(tier, "test")
    before = metrics.counter_total("pipeline.consistency_steps")
    degraded = pipe.generate(["a storm rolls in"], seed=1)
    assert degraded.shape[1] == cfg.sampler.image_size  # full res
    assert metrics.counter_total("pipeline.consistency_steps") \
        - before == CONSISTENCY_BROWNOUT_STEPS
    assert len(pipe._tier_fns) == 1
    with jit_sentinel.no_new_compiles():
        pipe.generate(["a storm rolls in"], seed=1)
    assert len(pipe._tier_fns) == 1
    with ladder._lock:
        ladder._step_to(0, "test")
    back = pipe.generate(["a storm rolls in"], seed=1)
    assert (back == full).all()


# -- real-geometry distillation (slow tier) -----------------------------------


@pytest.mark.slow
def test_distill_step_compiles_at_larger_geometry():
    """A closer-to-real geometry (deeper channels, 16² latents, longer
    solver schedule) through the same jitted distill step — the compile
    path the toy smoke doesn't stress. Slow tier: one extra UNet-pair
    compile (~a minute on a small host)."""
    base = _tiny_config()
    cfg = base.replace(models=dataclasses.replace(
        base.models, unet=dataclasses.replace(
            base.models.unet, base_channels=64)))
    unet = UNet(cfg.models.unet)
    lat = jnp.zeros((2, 16, 16, 4))
    t = jnp.zeros((2,), jnp.int32)
    ctx = jnp.zeros((2, 6, cfg.models.unet.context_dim))
    teacher = init_params(unet, 0, lat, t, ctx)
    trainer = ConsistencyDistillTrainer(cfg, mesh=None, lr=1e-3,
                                        solver_steps=50, skip=5)
    student, ema, opt = trainer.init_state(teacher)
    batch = {
        "latents": jax.random.normal(jax.random.PRNGKey(1),
                                     (2, 16, 16, 4)),
        "context": jax.random.normal(
            jax.random.PRNGKey(2), (2, 6, cfg.models.unet.context_dim)),
    }
    losses = []
    for i in range(2):
        student, ema, opt, loss = trainer.step(
            student, ema, opt, teacher, batch, jax.random.PRNGKey(i))
        losses.append(loss)
    assert np.isfinite(np.asarray(jnp.stack(losses))).all()
