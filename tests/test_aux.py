"""Aux subsystem tests: retry, checkpoint/resume, param save/load cache."""

import asyncio

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from cassmantle_tpu.utils.retry import linear_backoff, retry_async


@pytest.mark.asyncio
async def test_retry_succeeds_after_failures():
    attempts = []

    async def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise RuntimeError("transient")
        return "ok"

    sleeps = []

    async def fake_sleep(s):
        sleeps.append(s)

    result = await retry_async(
        flaky, max_retries=5, backoff=linear_backoff(10.0),
        sleep=fake_sleep, jitter=False,
    )
    assert result == "ok"
    assert len(attempts) == 3
    assert sleeps == [10.0, 20.0]  # reference schedule (k+1)*base


@pytest.mark.asyncio
async def test_retry_full_jitter_spreads_and_replays_with_seeded_rng():
    """ISSUE 12 satellite: backoff pauses are full-jittered (uniform
    over (0, schedule]) so N callers tripped by one store blip don't
    re-dial in lockstep — and an injected RNG replays the exact same
    pause sequence (deterministic under drill seeds)."""
    import random

    async def always_fails():
        raise RuntimeError("transient")

    async def run(rng):
        sleeps = []

        async def sleep(v):
            sleeps.append(v)

        with pytest.raises(RuntimeError):
            await retry_async(always_fails, max_retries=4,
                              backoff=linear_backoff(10.0),
                              sleep=sleep, rng=rng)
        return sleeps

    a = await run(random.Random(7))
    b = await run(random.Random(7))
    c = await run(random.Random(8))
    assert a == b                      # seeded replay
    assert a != c                      # actually jittered
    for pause, bound in zip(a, (10.0, 20.0, 30.0)):
        assert 0.0 <= pause <= bound   # full jitter stays in-window


@pytest.mark.asyncio
async def test_retry_exhausts_and_raises():
    async def always_fails():
        raise ValueError("permanent")

    async def fake_sleep(s):
        pass

    with pytest.raises(ValueError):
        await retry_async(always_fails, max_retries=3, sleep=fake_sleep)


def test_param_save_load_roundtrip(tmp_path):
    from cassmantle_tpu.models.weights import load_params, save_params

    tree = {"params": {"layer": {"kernel": np.ones((4, 4), np.float32),
                                 "bias": np.zeros((4,), np.float32)}}}
    path = str(tmp_path / "cache.safetensors")
    save_params(tree, path)
    back = load_params(path)
    np.testing.assert_array_equal(
        back["params"]["layer"]["kernel"], tree["params"]["layer"]["kernel"]
    )


def test_init_params_cached_uses_cache(tmp_path, cfg):
    import flax.linen as nn

    from cassmantle_tpu.models.weights import init_params_cached

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(8)(x)

    model = Tiny()
    x = jnp.ones((1, 4))
    path = str(tmp_path / "tiny.safetensors")
    p1 = init_params_cached(model, 0, x, cache_path=path)
    p2 = init_params_cached(model, 0, x, cache_path=path)
    np.testing.assert_array_equal(
        np.asarray(p1["params"]["Dense_0"]["kernel"]),
        np.asarray(p2["params"]["Dense_0"]["kernel"]),
    )
    import os

    assert os.path.exists(path)


def test_train_checkpoint_roundtrip(tmp_path):
    from cassmantle_tpu.utils.checkpoint import TrainCheckpointer

    params = {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)}
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(1, params, opt_state)
    ckpt.save(2, {"w": params["w"] * 2}, opt_state)
    assert ckpt.latest_step() == 2
    restored = ckpt.restore(
        template={"params": params, "opt_state": opt_state}
    )
    np.testing.assert_allclose(
        np.asarray(restored["params"]["w"]), np.asarray(params["w"]) * 2
    )
    ckpt.close()


def test_checkpoint_restores_across_mesh_shapes(tmp_path):
    """Elastic resume: a state saved under one mesh restores into a
    DIFFERENT mesh's shardings (orbax StandardRestore reshards to the
    template) — the grow-the-slice / degraded-slice recovery path."""
    import jax

    from cassmantle_tpu.config import MeshConfig
    from cassmantle_tpu.parallel.mesh import batch_sharding, make_mesh
    from cassmantle_tpu.utils.checkpoint import TrainCheckpointer

    devices = jax.devices()
    mesh_a = make_mesh(MeshConfig(dp=2, pp=1, tp=1, sp=1, ep=1),
                       devices=devices[:2])
    mesh_b = make_mesh(MeshConfig(dp=4, pp=1, tp=1, sp=1, ep=1),
                       devices=devices[:4])
    w = jnp.arange(16.0).reshape(8, 2)
    wa = jax.device_put(w, batch_sharding(mesh_a))

    ckpt = TrainCheckpointer(str(tmp_path / "ck"))
    ckpt.save(1, {"w": wa}, opt_state=())
    template = {
        "params": {"w": jax.device_put(jnp.zeros_like(w),
                                       batch_sharding(mesh_b))},
        "opt_state": (),
    }
    restored = ckpt.restore(template=template)
    rw = restored["params"]["w"]
    assert rw.sharding.is_equivalent_to(batch_sharding(mesh_b), rw.ndim)
    np.testing.assert_allclose(np.asarray(rw), np.asarray(w))
    ckpt.close()


def test_cost_table_scan_aware():
    """tools/profile_unet.cost_table must multiply scan-body op costs
    by the trip count (a 50-step denoise scan is 50x its body, not 1x)
    and keep non-scan costs unscaled."""
    import importlib.util
    import os

    import jax
    import jax.numpy as jnp

    spec = importlib.util.spec_from_file_location(
        "profile_unet_mod",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "profile_unet.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    w = jnp.ones((8, 8), jnp.float32)

    def once(x):
        return x @ w

    def scanned(x):
        def body(carry, _):
            return carry @ w, None
        out, _ = jax.lax.scan(body, x, None, length=7)
        return out

    rows1, total1 = mod.cost_table(once, jnp.ones((4, 8)))
    rows7, total7 = mod.cost_table(scanned, jnp.ones((4, 8)))
    assert total7 == 7 * total1, (total1, total7)
    assert rows7[0]["count"] == 7 and rows1[0]["count"] == 1
