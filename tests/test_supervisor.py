"""Supervision subsystem units (ISSUE 2): circuit breaker state machine,
round reserve rotation, and the ServingSupervisor fusion — all on
injectable clocks, no sleeps."""

import pytest

from cassmantle_tpu.engine.reserve import RoundReserve
from cassmantle_tpu.engine.store import MemoryStore
from cassmantle_tpu.serving.supervisor import ServingSupervisor
from cassmantle_tpu.utils.circuit import CircuitBreaker, CircuitOpen


class FakeClock:
    def __init__(self) -> None:
        self.now = 1000.0

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


def make_breaker(clock, threshold=3, window=10.0, reset=5.0):
    return CircuitBreaker("test", failure_threshold=threshold,
                          window_s=window, reset_timeout_s=reset,
                          clock=clock)


# -- circuit breaker ---------------------------------------------------------

def test_breaker_trips_after_threshold_in_window():
    clock = FakeClock()
    b = make_breaker(clock)
    assert b.state == "closed"
    b.record_failure()
    b.record_failure()
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "open"
    assert not b.allow()


def test_breaker_window_slides():
    """Failures older than the window age out: 2 old + 2 fresh never
    reaches the 3-in-window threshold."""
    clock = FakeClock()
    b = make_breaker(clock, threshold=3, window=10.0)
    b.record_failure()
    b.record_failure()
    clock.advance(11.0)
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"


def test_breaker_half_open_probe_and_recovery():
    clock = FakeClock()
    b = make_breaker(clock, reset=5.0)
    for _ in range(3):
        b.record_failure()
    assert b.state == "open"
    clock.advance(5.0)
    assert b.state == "half_open"
    # exactly one probe admitted while its verdict is pending
    assert b.allow()
    assert not b.allow()
    b.record_success()
    assert b.state == "closed"
    assert b.allow()


def test_breaker_half_open_failure_reopens_with_fresh_cooldown():
    clock = FakeClock()
    b = make_breaker(clock, reset=5.0)
    for _ in range(3):
        b.record_failure()
    clock.advance(5.0)
    assert b.allow()            # half-open probe
    b.record_failure()
    assert b.state == "open"
    assert b.seconds_until_half_open() == pytest.approx(5.0)
    clock.advance(4.0)
    assert not b.allow()
    clock.advance(1.0)
    assert b.allow()


def test_breaker_hung_probe_expires():
    """A half-open probe that never reports (wedged device call) must not
    wedge the breaker: after another cooldown a new probe is admitted."""
    clock = FakeClock()
    b = make_breaker(clock, reset=5.0)
    for _ in range(3):
        b.record_failure()
    clock.advance(5.0)
    assert b.allow()            # probe #1 dispatched, never reports
    assert not b.allow()
    clock.advance(5.0)
    assert b.allow()            # probe slot recycled


def test_breaker_snapshot_shape():
    clock = FakeClock()
    b = make_breaker(clock)
    snap = b.snapshot()
    assert snap["state"] == "closed"
    assert snap["retry_after_s"] == 0.0
    for _ in range(3):
        b.record_failure()
    snap = b.snapshot()
    assert snap["state"] == "open"
    assert snap["retry_after_s"] > 0.0


# -- supervisor fusion -------------------------------------------------------

def test_supervisor_degrades_on_open_breaker():
    clock = FakeClock()
    sup = ServingSupervisor(
        content_breaker=make_breaker(clock), clock=clock)
    assert not sup.degraded
    for _ in range(3):
        sup.content_breaker.record_failure()
    assert sup.degraded
    status = sup.status()
    assert status["ready"] is False and status["state"] == "degraded"
    assert status["breakers"]["test"]["state"] == "open"
    sup.content_breaker.record_success()


def test_supervisor_watchdog_overrun_degrades_then_expires():
    clock = FakeClock()
    sup = ServingSupervisor(degraded_cooldown_s=30.0, clock=clock)
    assert not sup.watchdog_degraded
    sup.note_dispatch_overrun("score")
    assert sup.watchdog_degraded and sup.degraded
    assert sup.retry_after_s() == pytest.approx(30.0)
    status = sup.status()
    assert status["watchdog"]["degraded"] and \
        status["watchdog"]["overruns"] == 1
    clock.advance(31.0)
    assert not sup.watchdog_degraded and not sup.degraded
    assert sup.status()["ready"] is True


def test_supervisor_device_verdict_flips_ready():
    clock = FakeClock()
    sup = ServingSupervisor(clock=clock)
    assert sup.status(device_ok=True)["ready"] is True
    assert sup.status(device_ok=None)["ready"] is True   # nothing to probe
    assert sup.status(device_ok=False)["ready"] is False


def test_supervisor_shed_scores_only_when_open():
    clock = FakeClock()
    sup = ServingSupervisor(
        score_breaker=make_breaker(clock, reset=5.0), clock=clock)
    assert not sup.shed_scores()
    for _ in range(3):
        sup.score_breaker.record_failure()
    assert sup.shed_scores()
    assert sup.retry_after_s() >= 1.0
    clock.advance(5.0)           # half-open: probe traffic flows again
    assert not sup.shed_scores()


# -- round reserve -----------------------------------------------------------

@pytest.mark.asyncio
async def test_reserve_rotates_least_recently_played():
    store = MemoryStore()
    reserve = RoundReserve(store, capacity=4)
    for i in range(3):
        await reserve.archive(f"text {i}", f'{{"round": {i}}}',
                              f"jpeg{i}".encode())
    assert await reserve.size() == 3
    # round 2 is on screen: first pick must be the oldest-seen (round 0)
    text, prompt, image = await reserve.pick(exclude=b'{"round": 2}')
    assert prompt == b'{"round": 0}' and text == "text 0" and image == b"jpeg0"
    # consecutive degraded promotions serve DIFFERENT rounds
    _, prompt2, _ = await reserve.pick(exclude=prompt)
    assert prompt2 == b'{"round": 1}'
    _, prompt3, _ = await reserve.pick(exclude=prompt2)
    assert prompt3 != prompt2
    # the full rotation cycles rather than pinning one round
    seen = {bytes(prompt), bytes(prompt2), bytes(prompt3)}
    assert len(seen) == 3


@pytest.mark.asyncio
async def test_reserve_capacity_ring_overwrites_oldest():
    store = MemoryStore()
    reserve = RoundReserve(store, capacity=2)
    for i in range(5):
        await reserve.archive(f"text {i}", f"prompt {i}", b"j")
    assert await reserve.size() == 2
    picked = set()
    for _ in range(2):
        _, prompt, _ = await reserve.pick()
        picked.add(bytes(prompt))
    assert picked == {b"prompt 3", b"prompt 4"}


@pytest.mark.asyncio
async def test_reserve_skips_consecutive_duplicates():
    store = MemoryStore()
    reserve = RoundReserve(store, capacity=4)
    await reserve.archive("same", "p1", b"j1")
    await reserve.archive("same", "p1-replayed", b"j1")
    assert await reserve.size() == 1


@pytest.mark.asyncio
async def test_reserve_empty_and_all_excluded():
    store = MemoryStore()
    reserve = RoundReserve(store, capacity=2)
    assert await reserve.pick() is None
    await reserve.archive("only", "p", b"j")
    assert await reserve.pick(exclude=b"p") is None
    assert (await reserve.pick())[1] == b"p"


def test_circuit_open_is_an_exception():
    with pytest.raises(CircuitOpen):
        raise CircuitOpen("content")
