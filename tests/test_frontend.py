"""Frontend wiring tests — the last untested layer (reference
script.js:97-442 behaviors live in static/app.js here).

This container ships NO JavaScript runtime and NO browser (checked:
node/bun/deno/quickjs/duktape/chromium all absent), so app.js cannot be
*executed* in CI. What CAN be executed is every contract the script
depends on, plus structural checks on the flows themselves:

1. endpoint contract — every URL app.js fetches (or opens a WebSocket
   to) must be served by the real aiohttp app over the fake backend,
   with the response shape the script destructures;
2. DOM contract — every element id app.js touches via $()/
   getElementById must exist in static/index.html, and the css classes
   it toggles must exist in style.css;
3. flow wiring — the reset-triggered refetch, mask-input wiring,
   per-word spellcheck hold, and win flow are asserted at the source
   level (the regression classes VERDICT r2 named).

A change that renames a route, drops a DOM node, or re-batches the
spellcheck hold fails here even though no JS runs.
"""

import json
import os
import re

import pytest

from tests.test_server import make_cfg, make_client

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STATIC = os.path.join(REPO, "static")

APP_JS = open(os.path.join(STATIC, "app.js")).read()
INDEX_HTML = open(os.path.join(STATIC, "index.html")).read()
STYLE_CSS = open(os.path.join(STATIC, "style.css")).read()


# ---------------------------------------------------------------- contracts

def referenced_http_paths():
    """Every path app.js fetches (http) — the client/server contract."""
    return sorted(set(re.findall(r"fetch\(\"(/[^\"]*)\"", APP_JS)))


@pytest.mark.asyncio
async def test_every_fetched_endpoint_is_served():
    paths = referenced_http_paths()
    # the script must still be calling the reference API surface at all
    assert {"/client/status", "/init", "/fetch/contents",
            "/compute_score", "/wordlist"} <= set(paths)

    client, game = await make_client(make_cfg())
    try:
        await client.get("/init")
        mask = (await game.fetch_prompt_json("x"))["masks"][0]
        for path in paths:
            if path == "/compute_score":
                res = await client.post(
                    path, json={"inputs": {str(mask): "stormy"}})
            else:
                res = await client.get(path)
            assert res.status == 200, (path, res.status)
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_ws_clock_message_shape():
    """connectClock destructures {time, conns, reset} from /clock and
    splits time as mm:ss — the push contract."""
    assert "/clock" in APP_JS and "WebSocket" in APP_JS
    client, _ = await make_client(make_cfg())
    try:
        ws = await client.ws_connect("/clock")
        msg = json.loads((await ws.receive(timeout=10)).data)
        assert {"time", "conns", "reset"} <= set(msg)
        assert re.fullmatch(r"\d{2}:\d{2}", msg["time"])
        await ws.close()
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_fetch_contents_has_fields_the_script_renders():
    """fetchContents() reads data.image / data.story.title /
    data.prompt.{tokens,masks,scores,correct,attempts} — all present."""
    client, _ = await make_client(make_cfg())
    try:
        await client.get("/init")
        data = await (await client.get("/fetch/contents")).json()
        assert set(data) >= {"image", "prompt", "story"}
        assert set(data["prompt"]) >= {"tokens", "masks", "scores",
                                       "correct", "attempts"}
        assert "title" in data["story"]
    finally:
        await client.close()


def test_every_dom_id_exists_in_index_html():
    ids = set(re.findall(r"\$\(\"([\w-]+)\"\)", APP_JS))
    ids |= set(re.findall(r"getElementById\(\"([\w-]+)\"\)", APP_JS))
    assert {"clock", "prompt", "submit", "feedback",
            "win-banner", "round-image"} <= ids
    html_ids = set(re.findall(r"id=\"([\w-]+)\"", INDEX_HTML))
    missing = ids - html_ids
    assert not missing, f"app.js touches ids absent from index.html: {missing}"


def test_css_classes_the_script_toggles_exist():
    toggled = set(re.findall(
        r"classList\.(?:add|remove|toggle)\(\"([\w-]+)\"", APP_JS))
    assert {"hidden", "blink", "solved"} <= toggled
    for cls in toggled:
        assert re.search(rf"\.{cls}\b", STYLE_CSS), \
            f"app.js toggles .{cls} but style.css never styles it"


def test_index_html_loads_the_scripts():
    for asset in ("app.js", "spell.js", "style.css"):
        assert asset in INDEX_HTML
        assert os.path.exists(os.path.join(STATIC, asset))


# ------------------------------------------------------------- flow wiring

def _block(src, start, end="\n}"):
    """Slice from `start` to the next `end` marker — with the repo's
    2-space indent style, "\n}" delimits a top-level function and
    "\n  }"/"\n    }" delimit blocks nested 1/2 levels deep."""
    i = src.index(start)
    return src[i:src.index(end, i)]


def test_reset_triggers_refetch_and_state_clear():
    """WS reset flag → clear won/holds, hide banner, refetch content
    (reference script.js:125-134 behavior)."""
    onmsg = _block(APP_JS, "ws.onmessage")
    reset = _block(onmsg, "if (data.reset)", "\n    }")
    assert "fetchContents()" in reset
    assert "state.won = false" in reset
    assert "state.confirmed.clear()" in reset
    assert "win-banner" in reset


def test_mask_input_wiring():
    """renderPrompt puts inputs at mask indices tagged with the mask
    index; submitGuesses keys the POST body by that same tag."""
    render = _block(APP_JS, "function renderPrompt")
    assert "input.dataset.mask = idx" in render
    submit = _block(APP_JS, "async function submitGuesses", "\n}")
    assert "inputs[input.dataset.mask] = word" in submit
    assert '"/compute_score"' in submit
    assert "JSON.stringify({ inputs })" in submit


def test_spell_hold_is_per_word():
    """ADVICE r2: only the word whose hint is DISPLAYED may be
    confirmed; batch-confirming would let other flagged words pass on
    the next submit without the player ever seeing their suggestions."""
    submit = _block(APP_JS, "async function submitGuesses", "\n}")
    hold = _block(submit, "if (fresh.length)", "\n  }")
    assert "state.confirmed.add(fresh[0].word)" in hold
    assert "fresh[0].hint" in hold
    # no bulk confirm anywhere in the submit path
    assert not re.search(r"fresh\.forEach[^\n]*confirmed\.add", submit)


def test_win_flow():
    submit = _block(APP_JS, "async function submitGuesses", "\n}")
    assert "scores.won === 1" in submit
    win = _block(submit, "if (state.won)", "\n    }")
    assert "win-banner" in win and "remove" in win
