"""Encoder propagation: the secondary serving-path smokes.

Split from tests/test_encprop.py (slow tier, tests/conftest.py map):
each of these compiles another whole tiny pipeline, and the tier-1
acceptance bars — stride-1 bit-parity, the quality-gate mechanism,
key-schedule accounting, batched-decoder equivalence, kill switch,
jit sentinel, decode-kernel parity — already run in the default tier.
These cover the remaining serving shapes end to end: the non-trivial
default-style key schedule through the quality report, the composed
deepcache+encprop pipeline, the encprop preset with the fused VAE,
batched-vs-sequential propagated-decoder equivalence through the real
UNet, the CASSMANTLE_NO_ENCPROP kill-switch revert, and the
pipeline.encprop_* diagnosis counters.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.ops.ddim import (
    DDIMSchedule,
    ddim_sample_encprop,
    encprop_key_indices,
    make_cfg_denoiser_encprop,
)


@pytest.fixture(scope="module")
def plain_pipe():
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    return Text2ImagePipeline(_tiny_config())


def _tiny_unet():
    from cassmantle_tpu.models.unet import UNet
    from cassmantle_tpu.models.weights import init_params

    cfg = _tiny_config().models.unet
    model = UNet(cfg)
    lat = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 4))
    t = jnp.array([5, 9], jnp.int32)
    ctx = jax.random.normal(jax.random.PRNGKey(1), (2, 6, cfg.context_dim))
    params = init_params(model, 0, lat, t, ctx, None)
    return model, params, lat, t, ctx, None


def _encprop_cfg(stride=1, dense=0, **sampler_kw):
    cfg = _tiny_config()
    return cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, encprop=True, encprop_stride=stride,
        encprop_dense_steps=dense, **sampler_kw))


def test_pipeline_default_schedule_quality_report(plain_pipe):
    """The default (non-trivial) key schedule flows through the quality
    gate end to end; on random init the verdict is advisory
    (gate_enforced False) but every field must compute."""
    from cassmantle_tpu.eval.clip_parity import (
        ClipSimilarityHarness,
        encprop_quality_report,
    )
    from cassmantle_tpu.models.clip_vision import ClipVisionConfig
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    prompts = ["a quiet harbor at dawn"]
    enc = Text2ImagePipeline(_encprop_cfg(stride=2, dense=1),
                             share_params_with=plain_pipe)
    a = plain_pipe.generate(prompts, seed=3)
    b = enc.generate(prompts, seed=3)
    harness = ClipSimilarityHarness(
        text_cfg=_tiny_config().models.clip_text,
        vision_cfg=ClipVisionConfig(
            image_size=32, patch_size=8, hidden_size=64,
            intermediate_size=128, num_layers=2, num_heads=4,
            projection_dim=64),
        pad_len=16)
    report = encprop_quality_report(harness, b, a, prompts)
    for field in ("image_sim_mean", "image_sim_min", "clip_sim_encprop",
                  "clip_sim_full", "floor"):
        assert np.isfinite(report[field]), field
    assert report["gate_enforced"] is False


def test_composed_pipeline_runs():
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    cfg = _tiny_config()
    cfg = cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, encprop=True, encprop_stride=4,
        encprop_dense_steps=0, deepcache=True))
    imgs = Text2ImagePipeline(cfg).generate(["a bridge in fog"], seed=2)
    assert imgs.shape[-1] == 3 and imgs.dtype == np.uint8


def test_encprop_preset_with_fused_vae_runs(plain_pipe):
    """The encprop_serving_config shape — encprop sampler + fused VAE —
    through the tiny pipeline; fused VAE shares the plain pipeline's
    param tree (arch()-keyed compatibility)."""
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    cfg = _encprop_cfg(stride=2, dense=1)
    cfg = cfg.replace(models=dataclasses.replace(
        cfg.models, vae=dataclasses.replace(cfg.models.vae,
                                            fused_conv=True)))
    pipe = Text2ImagePipeline(cfg, share_params_with=plain_pipe)
    imgs = pipe.generate(["a lighthouse in rain"], seed=9)
    assert imgs.shape[-1] == 3 and imgs.dtype == np.uint8


def test_batched_prop_decoder_equals_sequential():
    """One batched decoder forward for a segment's propagated steps must
    equal per-step decoder forwards — through the REAL tiny UNet and the
    real cache tiling (make_cfg_denoiser_encprop), end to end through
    the sampler."""
    model, params, lat_b2, t, ctx, add = _tiny_unet()
    lat = jax.random.normal(jax.random.PRNGKey(8), (1, 8, 8, 4))
    cond, uncond = ctx[:1], jnp.zeros_like(ctx[:1])
    schedule = DDIMSchedule.create(6)
    dk, dp, _ = make_cfg_denoiser_encprop(
        model.apply, params, cond, uncond, 5.0)

    # direct: a 2-step prop batch equals the two single-step calls.
    # Tolerance is fp32-reassociation-sized, not bitwise: the backend
    # may tile/thread a batch-4 matmul differently than a batch-2 one
    # (observed ~1e-5 on the 8-virtual-device CPU env); the CLAIM is
    # per-row computation independence, which these bounds pin.
    _, cache = dk(lat, schedule.timesteps[0])
    ts = schedule.timesteps[1:3]
    batched = dp(cache, ts)
    one = dp(cache, ts[:1])
    two = dp(cache, ts[1:2])
    np.testing.assert_allclose(np.asarray(batched[0]), np.asarray(one[0]),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(batched[1]), np.asarray(two[0]),
                               atol=1e-4, rtol=1e-4)

    # and through the whole sampler: batch_props on vs off
    out_b = ddim_sample_encprop(dk, dp, lat, schedule, stride=3,
                                dense_steps=0, batch_props=True)
    out_s = ddim_sample_encprop(dk, dp, lat, schedule, stride=3,
                                dense_steps=0, batch_props=False)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_s),
                               atol=1e-4, rtol=1e-4)


def test_kill_switch_reverts_to_full_forwards(plain_pipe, monkeypatch):
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline
    from cassmantle_tpu.utils.logging import metrics

    monkeypatch.setenv("CASSMANTLE_NO_ENCPROP", "1")
    killed = Text2ImagePipeline(_encprop_cfg(stride=2, dense=0),
                                share_params_with=plain_pipe)
    before = dict(metrics.snapshot()["counters"])
    out = killed.generate(["a quiet harbor at dawn"], seed=3)
    after = dict(metrics.snapshot()["counters"])
    np.testing.assert_array_equal(
        out, plain_pipe.generate(["a quiet harbor at dawn"], seed=3))
    # the diagnosis counters must not claim encprop ran
    assert after.get("pipeline.encprop_key_steps", 0) == \
        before.get("pipeline.encprop_key_steps", 0)


def test_encprop_diagnosis_counters(plain_pipe):
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline
    from cassmantle_tpu.utils.logging import metrics

    enc = Text2ImagePipeline(_encprop_cfg(stride=2, dense=0),
                             share_params_with=plain_pipe)
    before = dict(metrics.snapshot()["counters"])
    enc.generate(["a quiet harbor at dawn"], seed=4)
    after = dict(metrics.snapshot()["counters"])
    n = _tiny_config().sampler.num_steps
    keys = len(encprop_key_indices(n, 2, 0))
    assert after.get("pipeline.encprop_key_steps", 0) - \
        before.get("pipeline.encprop_key_steps", 0) == keys
    assert after.get("pipeline.encprop_prop_steps", 0) - \
        before.get("pipeline.encprop_prop_steps", 0) == n - keys
