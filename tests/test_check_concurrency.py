"""Concurrency lint + runtime deadlock-sentinel gate (fast tier).

Golden fixture snippets pin each rule of the three
``cassmantle_tpu/analysis`` concurrency passes (known violations must
fail; suppressed / executor-routed / consistently-ordered variants must
pass), the repo itself must lint clean through the real entry points
(``tools/check_concurrency.py``, ``tools/lint_all.py``), and the
``utils/locks.OrderedLock`` sentinel must raise on seeded inversions —
including the PR 1 dispatch-deadlock shape, pinned here as a regression
fixture for the static pass AND as a runtime cross-thread inversion.
"""

import textwrap
import threading

import pytest

from cassmantle_tpu.analysis.asyncblock import AsyncBlockingPass
from cassmantle_tpu.analysis.core import parse_source, run_passes
from cassmantle_tpu.analysis.hostsync import HostSyncPass
from cassmantle_tpu.analysis.lockorder import LockOrderPass
from cassmantle_tpu.utils import locks
from cassmantle_tpu.utils.locks import LockOrderViolation, OrderedLock


def lint(src, *passes, rel="<fixture>"):
    return run_passes([parse_source(textwrap.dedent(src), rel)],
                      list(passes))


def rules(findings):
    return [f.rule for f in findings]


# -- lock-order pass ---------------------------------------------------------

def test_direct_lock_order_cycle_fails():
    findings = lint("""
        import threading

        class P:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def x(self):
                with self._a:
                    with self._b:
                        pass

            def y(self):
                with self._b:
                    with self._a:
                        pass
    """, LockOrderPass())
    assert rules(findings) == ["lock-order-cycle"]
    assert "P._a" in findings[0].message and "P._b" in findings[0].message


def test_pr1_dispatch_deadlock_shape_is_caught():
    """Regression fixture: the PR 1 deadlock — two call paths acquiring
    the pipeline/dispatch lock pair in opposite order, nested only
    THROUGH method calls (inter-procedural), exactly how the real hang
    hid from review."""
    findings = lint("""
        import threading

        class Backend:
            def __init__(self):
                self._pipeline_lock = threading.Lock()
                self._dispatch_lock = threading.Lock()

            def generate(self):
                with self._pipeline_lock:
                    self._dispatch()

            def _dispatch(self):
                with self._dispatch_lock:
                    pass

            def score(self):
                with self._dispatch_lock:
                    self._finish()

            def _finish(self):
                with self._pipeline_lock:
                    pass
    """, LockOrderPass())
    assert rules(findings) == ["lock-order-cycle"]
    msg = findings[0].message
    assert "Backend._pipeline_lock" in msg
    assert "Backend._dispatch_lock" in msg


def test_consistent_lock_order_is_clean():
    findings = lint("""
        import threading

        class Backend:
            def __init__(self):
                self._pipeline_lock = threading.Lock()
                self._dispatch_lock = threading.Lock()

            def generate(self):
                with self._pipeline_lock:
                    self._dispatch()

            def _dispatch(self):
                with self._dispatch_lock:
                    pass

            def score(self):
                with self._pipeline_lock:
                    with self._dispatch_lock:
                        pass
    """, LockOrderPass())
    assert findings == []


def test_self_reacquire_through_helper_fails_for_lock_not_rlock():
    src = """
        import threading

        class S:
            def __init__(self):
                self._l = threading.{kind}()

            def a(self):
                with self._l:
                    self.b()

            def b(self):
                with self._l:
                    pass
    """
    bad = lint(src.format(kind="Lock"), LockOrderPass())
    assert rules(bad) == ["lock-order-cycle"]
    assert "re-acquired" in bad[0].message
    assert lint(src.format(kind="RLock"), LockOrderPass()) == []


def test_lock_across_await_fails():
    findings = lint("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()

            async def run(self, thing):
                with self._lock:
                    await thing()
    """, LockOrderPass())
    assert rules(findings) == ["lock-across-await"]


def test_lock_across_blocking_call_fails_and_suppression_passes():
    src = """
        import threading
        import time

        class Q:
            def __init__(self):
                self._lock = threading.Lock()

            def run(self, fut):
                with self._lock:
                    time.sleep(1.0){sup}
                    fut.result(){sup}
    """
    findings = lint(src.format(sup=""), LockOrderPass())
    assert rules(findings) == ["lock-blocking-call", "lock-blocking-call"]
    sup = "  # lint: ignore[lock-blocking-call] — fixture reason"
    assert lint(src.format(sup=sup), LockOrderPass()) == []


def test_bounded_wait_under_lock_is_clean():
    findings = lint("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()

            def run(self, fut):
                with self._lock:
                    fut.result(timeout=1.0)
    """, LockOrderPass())
    assert findings == []


# -- blocking-call-in-async pass ---------------------------------------------

def test_blocking_calls_in_async_fail():
    findings = lint("""
        import time
        import requests

        async def handler(fut, path):
            time.sleep(1.0)
            fut.result()
            requests.get("http://x")
            open(path).read()
    """, AsyncBlockingPass())
    assert rules(findings) == ["async-blocking-call"] * 4


def test_awaited_and_executor_routed_variants_pass():
    findings = lint("""
        import asyncio
        import time

        async def handler(loop, cond, fut):
            await asyncio.sleep(1.0)
            await loop.run_in_executor(None, time.sleep, 1.0)
            await asyncio.wait_for(cond.wait(), timeout=0.1)
            fut.result(timeout=1.0)

            def sync_helper():
                time.sleep(1.0)  # runs on an executor thread

            await loop.run_in_executor(None, sync_helper)
    """, AsyncBlockingPass())
    assert findings == []


def test_async_suppression_and_dir_scoping():
    src = """
        import time

        async def handler():
            time.sleep(1.0)
    """
    scoped = AsyncBlockingPass.for_repo()
    # outside the event-loop layers: not scanned
    assert lint(src, scoped, rel="cassmantle_tpu/models/x.py") == []
    # inside: scanned and failing
    assert rules(lint(src, scoped,
                      rel="cassmantle_tpu/server/x.py")) == \
        ["async-blocking-call"]
    sup = src.replace(
        "time.sleep(1.0)",
        "time.sleep(1.0)  # lint: ignore[async-blocking-call] — why")
    assert lint(sup, scoped, rel="cassmantle_tpu/server/x.py") == []


# -- host-sync pass ----------------------------------------------------------

def test_sync_in_jit_region_fails():
    findings = lint("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return float(x)

        def g(x):
            return np.asarray(x)

        g_jit = jax.jit(g)
    """, HostSyncPass())
    assert rules(findings) == ["host-sync", "host-sync"]


def test_jit_detection_through_wrappers_and_transitive_calls():
    findings = lint("""
        import jax
        from functools import partial

        class Pipe:
            def __init__(self, mesh):
                self._sample = dp_sharded_sampler(self._sample_impl, mesh)
                self._i2i = jax.jit(partial(self._img2img_impl, 1))

            def _sample_impl(self, params, ids):
                return self._helper(ids)

            def _helper(self, ids):
                return ids.item()

            def _img2img_impl(self, k, lat):
                return int(lat[0])
    """, HostSyncPass())
    assert rules(findings) == ["host-sync", "host-sync"]
    assert any("_helper" in f.message for f in findings)
    assert any("_img2img_impl" in f.message for f in findings)


def test_sync_in_host_loop_fails_but_boundary_sync_passes():
    findings = lint("""
        import numpy as np

        def stage(xs):
            out = []
            for x in xs:
                out.append(np.asarray(x))   # one sync per iteration
            return out

        def boundary(x):
            return np.asarray(x)            # the collect-once sync
    """, HostSyncPass())
    assert rules(findings) == ["host-sync"]
    assert findings[0].message.startswith("np.asarray")


def test_config_reads_in_jit_are_not_syncs():
    findings = lint("""
        import jax

        @jax.jit
        def f(self, x):
            s = float(self.cfg.sampler.image_size)
            n = int(len(x))
            return s, n
    """, HostSyncPass())
    assert findings == []


def test_hostsync_suppression_above_line_passes():
    findings = lint("""
        import numpy as np

        def stage(xs):
            out = []
            for x in xs:
                # lint: ignore[host-sync] — fixture reason
                out.append(np.asarray(x))
            return out
    """, HostSyncPass())
    assert findings == []


# -- the repo itself lints clean ---------------------------------------------

def test_repo_is_concurrency_clean():
    from tools.check_concurrency import check

    assert check() == []


def test_check_concurrency_cli_exits_zero():
    from tools.check_concurrency import main

    assert main([]) == 0


def test_lint_all_runs_every_pass_with_one_exit_code(tmp_path):
    from tools.lint_all import main

    assert main([]) == 0
    # one dirty tree -> nonzero: a bad metric name AND a lock cycle
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import threading

        metrics.inc("nosegments")

        class P:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def x(self):
                with self._a:
                    with self._b:
                        pass

            def y(self):
                with self._b:
                    with self._a:
                        pass
    """))
    assert main([str(tmp_path)]) == 1


# -- stage-scheduler shape (serving/stages.py, ISSUE 6) ----------------------
# Golden fixtures pinning the two structural invariants of the staged
# denoise loop: NO host sync inside the step loop (control state lives
# in host-side numpy mirrors; the only device→host transfer is the
# decode stage's collect-once per batch), and NO lock held across a
# stage boundary (the scheduler lock covers lifecycle only — a lock
# held across a cross-stage .result() handoff serializes the graph and
# is one wedged stage away from deadlock).

def test_stage_step_loop_host_sync_shape():
    """The violating shape: a denoise loop that reads a device value
    back every step (sync-per-iteration serializes the whole step
    pipeline). The clean shape is the shipped one: per-step dispatches
    ride host-side mirrors, the one sync sits OUTSIDE the loop at the
    decode boundary."""
    findings = lint("""
        import numpy as np

        class Server:
            def denoise_loop(self, steps):
                for _ in range(steps):
                    self.lat = self.step(self.lat)
                    done = np.asarray(self.lat)   # sync per step
                return done
    """, HostSyncPass())
    assert rules(findings) == ["host-sync"]

    clean = lint("""
        import numpy as np

        class Server:
            def denoise_loop(self, steps):
                for _ in range(steps):
                    self.lat = self.step(self.lat)
                    self.steps_done += 1          # host mirror only
                return self.lat

            def decode_batch(self, rows):
                images = self.decode(rows)
                return np.asarray(images)         # collect-once boundary
    """, HostSyncPass())
    assert clean == []


def test_stage_lock_across_stage_boundary_fails():
    """A lock held across a cross-stage handoff (submit → .result())
    is flagged as a blocking call under a lock; the shipped shape —
    lifecycle-only critical section, handoff outside — is clean."""
    findings = lint("""
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()

            def process(self, unit):
                with self._lock:
                    fut = self.encode_q.submit(unit)
                    cond = fut.result()
                return cond
    """, LockOrderPass())
    assert rules(findings) == ["lock-blocking-call"]

    clean = lint("""
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()

            def ensure_started(self):
                with self._lock:
                    if not self.started:
                        self.start_threads()
                        self.started = True

            def process(self, unit):
                self.ensure_started()
                fut = self.encode_q.submit(unit)
                return fut.result(timeout=30.0)
    """, LockOrderPass())
    assert clean == []


def test_stage_locks_are_ranked():
    """The stage graph's three locks carry the documented hierarchy
    (docs/STATIC_ANALYSIS.md): scheduler lifecycle at 14 (between the
    pipeline dispatch tier and the worker tier), each stage's dedicated
    dispatch worker fanned out above the process-global worker's 20."""
    import dataclasses

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.queue import _DispatchWorker
    from cassmantle_tpu.serving.stages import StagedImageServer

    base = test_config()
    cfg = base.replace(serving=dataclasses.replace(
        base.serving, staged_serving=True))
    srv = StagedImageServer(
        cfg, None, encode_fn=lambda *a: None, decode_fn=lambda *a: None,
        unet_apply=lambda *a: None, tokenize=lambda p: None, vae_scale=8)
    assert isinstance(srv._lock, OrderedLock)
    assert (srv._lock.name, srv._lock.rank) == ("stage.scheduler", 14)
    enc = _DispatchWorker("stage.encode_dispatch", rank=21)._lock
    dec = _DispatchWorker("stage.decode_dispatch", rank=22)._lock
    assert (enc.name, enc.rank) == ("stage.encode_dispatch", 21)
    assert (dec.name, dec.rank) == ("stage.decode_dispatch", 22)


# -- OrderedLock runtime sentinel --------------------------------------------
# (the autouse conftest fixture arms raising mode + resets the graph)

def test_seeded_inversion_raises_with_both_sites():
    a = OrderedLock("sentinel_a")
    b = OrderedLock("sentinel_b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(LockOrderViolation) as exc:
            a.acquire()
    assert "sentinel_a" in str(exc.value)
    assert "sentinel_b" in str(exc.value)
    assert "deadlock" in str(exc.value)
    # the violating acquire did NOT take the lock: still free
    assert not a.locked()


def test_cross_thread_inversion_raises():
    """The PR 1 shape at runtime: thread 1 nests pipeline->dispatch,
    the main thread then nests dispatch->pipeline."""
    pipeline = OrderedLock("t_pipeline")
    dispatch = OrderedLock("t_dispatch")

    def worker():
        with pipeline:
            with dispatch:
                pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    with dispatch:
        with pytest.raises(LockOrderViolation):
            pipeline.acquire()


def test_rank_violation_raises_and_correct_order_passes():
    outer = OrderedLock("t_outer", rank=10)
    inner = OrderedLock("t_inner", rank=40)
    with outer:
        with inner:
            pass
    with inner:
        with pytest.raises(LockOrderViolation) as exc:
            outer.acquire()
    assert "rank" in str(exc.value)


def test_reacquire_raises():
    lock = OrderedLock("t_reacquire")
    with lock:
        with pytest.raises(LockOrderViolation) as exc:
            lock.acquire()
    assert "re-acquire" in str(exc.value)
    # release path stayed balanced: usable again
    with lock:
        pass


def test_log_only_mode_counts_violations():
    from cassmantle_tpu.utils.logging import metrics

    locks.enable_sentinel(raise_on_violation=False)
    a = OrderedLock("t_log_a")
    b = OrderedLock("t_log_b")
    with a:
        with b:
            pass
    before = metrics.snapshot()["counters"].get(
        "locks.order_violations", 0)
    with b:
        with a:  # inversion: logged + counted, not raised
            pass
    after = metrics.snapshot()["counters"]["locks.order_violations"]
    assert after == before + 1


def test_sentinel_disabled_skips_checks():
    locks.disable_sentinel()
    a = OrderedLock("t_off_a")
    b = OrderedLock("t_off_b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass  # no raise, no tracking


def test_production_locks_are_ordered_and_ranked():
    """The converted supervisor/queue/circuit/health locks carry the
    documented hierarchy (docs/STATIC_ANALYSIS.md), so the fault-
    injection suite runs them all under the sentinel."""
    from cassmantle_tpu.serving.queue import _DispatchWorker
    from cassmantle_tpu.serving.supervisor import ServingSupervisor
    from cassmantle_tpu.utils.circuit import CircuitBreaker
    from cassmantle_tpu.utils.health import DeviceHealth

    ranked = {
        _DispatchWorker()._lock: ("queue.dispatch_worker", 20),
        ServingSupervisor()._lock: ("supervisor", 30),
        CircuitBreaker("probe")._lock: ("circuit.probe", 40),
        DeviceHealth()._lock: ("health.device", 50),
    }
    for lock, (name, rank) in ranked.items():
        assert isinstance(lock, OrderedLock)
        assert lock.name == name
        assert lock.rank == rank
    # strictly increasing leaf-ward: dispatch worker < supervisor <
    # breaker < health cache
    ranks = [rank for _, rank in ranked.values()]
    assert ranks == sorted(ranks)


def test_overload_locks_are_ranked():
    """The overload control plane's two snapshot locks (ISSUE 13) sit
    leaf-ward of everything they can be read under (supervisor 30,
    breakers 40) and outward of the chaos plan leaf (60): limiter 54 <
    brownout 55 — both guard only numeric state, never device work."""
    from cassmantle_tpu.serving.overload import (
        AdaptiveLimiter,
        BrownoutLadder,
    )

    limiter = AdaptiveLimiter("t_rankcheck")._lock
    ladder = BrownoutLadder()._lock
    ranked = [
        (limiter, "overload.limiter.t_rankcheck", 54),
        (ladder, "overload.brownout", 55),
    ]
    for lock, name, rank in ranked:
        assert isinstance(lock, OrderedLock)
        assert (lock.name, lock.rank) == (name, rank)
    assert 50 < min(r for _, _, r in ranked) and \
        max(r for _, _, r in ranked) < 60


def test_fabric_locks_are_ranked():
    """The fabric's three snapshot locks (ISSUE 8) sit between the
    store-TTL tier (level 0) and the pipeline dispatch tier (10):
    directory 4 < replication 5 < membership 6 — short-hold in-memory
    snapshot guards, never held across an await or a store round trip
    (the golden fixtures below pin the violating shape)."""
    from cassmantle_tpu.engine.store import MemoryStore, ReplicatedStore
    from cassmantle_tpu.fabric.directory import RoomDirectory
    from cassmantle_tpu.fabric.membership import ClusterMembership

    directory = RoomDirectory(["r0"], workers=["w0"])._lock
    replication = ReplicatedStore([7070])._state_lock
    membership = ClusterMembership(MemoryStore(), "w0")._lock
    ranked = [
        (directory, "fabric.directory", 4),
        (replication, "fabric.replication", 5),
        (membership, "fabric.membership", 6),
    ]
    for lock, name, rank in ranked:
        assert isinstance(lock, OrderedLock)
        assert (lock.name, lock.rank) == (name, rank)
    assert [r for _, _, r in ranked] == sorted(r for _, _, r in ranked)
    assert max(r for _, _, r in ranked) < 10  # outermost of the ranked tiers


def test_store_failover_under_directory_lock_shape():
    """Golden fixture pair for the fabric's store-failover shape: a
    blocking store round trip (the failover probe) under the directory
    lock is a violation — a dead leader's connect timeout would stall
    every routing lookup in the worker; the shipped shape computes
    under the lock and does store I/O outside it."""
    findings = lint("""
        import threading

        class Directory:
            def __init__(self):
                self._lock = threading.Lock()

            def owner_with_failover(self, room):
                with self._lock:
                    fut = self.pool.submit(self.probe_leader)
                    leader = fut.result()
                    return self.ring[leader][room]
    """, LockOrderPass())
    assert rules(findings) == ["lock-blocking-call"]

    clean = lint("""
        import threading

        class Directory:
            def __init__(self):
                self._lock = threading.Lock()

            def owner(self, room):
                with self._lock:
                    ring = self.ring
                return self._lookup(ring, room)

            def failover(self):
                fut = self.pool.submit(self.probe_leader)
                leader = fut.result(timeout=5.0)
                with self._lock:
                    self.leader = leader
    """, LockOrderPass())
    assert clean == []


def test_lock_hierarchy_documented():
    import pathlib

    doc = pathlib.Path(__file__).resolve().parents[1] / "docs" / \
        "STATIC_ANALYSIS.md"
    text = doc.read_text()
    for name in ("pipeline.t2i_dispatch", "queue.dispatch_worker",
                 "supervisor", "circuit.<name>", "health.device",
                 "stage.scheduler", "stage.encode_dispatch",
                 "stage.decode_dispatch", "pipeline.staged_init",
                 "fabric.directory", "fabric.replication",
                 "fabric.membership"):
        assert name in text, f"lock {name} missing from hierarchy table"
    for rule in ("lock-order-cycle", "lock-across-await",
                 "lock-blocking-call", "async-blocking-call",
                 "host-sync", "metric-name"):
        assert rule in text, f"rule {rule} missing from catalog"
