"""Sampler family tests (ops/samplers.py): Euler and DPM-Solver++(2M)
against the DDIM baseline.

Key correctness property: for the probability-flow ODE with a *consistent*
epsilon field — denoise(x_t, t) returning exactly the eps that places x_t
on the trajectory of a fixed x0 — every solver must recover x0 (the ODE's
solution keeps x0 invariant). This validates coefficients, spacing, and
VP/k-space conversions without any model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.ops.ddim import DDIMSchedule
from cassmantle_tpu.ops.samplers import (
    SAMPLER_KINDS,
    DPMppSchedule,
    EulerSchedule,
    _alpha_bars,
    make_sampler,
)

X0 = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 3))
AB = jnp.asarray(_alpha_bars().astype(np.float32))


def consistent_denoise(x, t):
    """eps such that x = sqrt(ab)*x0 + sqrt(1-ab)*eps."""
    ab = AB[t]
    return (x - jnp.sqrt(ab) * X0) / jnp.sqrt(1.0 - ab)


@pytest.mark.parametrize("kind", SAMPLER_KINDS)
def test_solver_recovers_x0_under_consistent_field(kind):
    sample = make_sampler(kind, 25)
    noise = jax.random.normal(jax.random.PRNGKey(1), X0.shape)
    out = sample(consistent_denoise, noise)
    np.testing.assert_allclose(np.asarray(out), np.asarray(X0),
                               atol=5e-2, rtol=5e-2)


@pytest.mark.parametrize("kind", ("euler", "dpmpp_2m"))
def test_solver_jits_and_is_deterministic(kind):
    sample = make_sampler(kind, 8)
    noise = jax.random.normal(jax.random.PRNGKey(2), X0.shape)
    f = jax.jit(lambda n: sample(consistent_denoise, n))
    a, b = f(noise), f(noise)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.isfinite(np.asarray(a)).all()


def test_dpmpp_schedule_coefficients_finite():
    s = DPMppSchedule.create(20)
    for name in ("alphas", "sigmas", "c_skip", "c_d0", "c_d1"):
        arr = np.asarray(getattr(s, name))
        assert np.isfinite(arr).all(), name
    # final step: c_skip 0 (sigma_next = 0), first-order (c_d1 = 0)
    assert np.asarray(s.c_skip)[-1] == 0.0
    assert np.asarray(s.c_d1)[-1] == 0.0
    assert np.asarray(s.c_d1)[0] == 0.0  # multistep warmup


def test_dpmpp_2m_interior_coefficients_match_formula():
    """Regression for the 2M correction weight: for an interior step,
    c_d0/c_d1 must equal the DPM-Solver++(2M) formula with weight
    1/(2·r0), r0 = h_prev/h (computed independently here)."""
    steps = 10
    s = DPMppSchedule.create(steps)
    ab = _alpha_bars()
    ts = np.asarray(s.timesteps)
    i = 5  # interior: not warmup, not final
    a = np.sqrt(ab[ts])
    sg = np.sqrt(1.0 - ab[ts])
    lam = np.log(a) - np.log(sg)
    a_next, sg_next = a[i + 1], sg[i + 1]
    lam_next = np.log(a_next) - np.log(sg_next)
    h = lam_next - lam[i]
    h_prev = lam[i] - lam[i - 1]
    r0 = h_prev / h
    em1 = np.expm1(-h)
    w = 1.0 / (2.0 * r0)
    np.testing.assert_allclose(
        float(np.asarray(s.c_d0)[i]), -a_next * em1 * (1.0 + w), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(np.asarray(s.c_d1)[i]), a_next * em1 * w, rtol=1e-5
    )
    np.testing.assert_allclose(
        float(np.asarray(s.c_skip)[i]), sg_next / sg[i], rtol=1e-5
    )


def test_euler_schedule_monotone():
    s = EulerSchedule.create(30)
    sig = np.asarray(s.sigmas)
    assert sig[-1] == 0.0
    assert (np.diff(sig) < 0).all()
    assert len(np.asarray(s.timesteps)) == 30


def curved_denoise(x, t):
    """eps field with t-dependent curvature (the consistent field is exact
    for every solver, so order-of-accuracy needs a curved target)."""
    ab = AB[t]
    x0_t = X0 * (1.0 + 0.3 * jnp.sin(t.astype(jnp.float32) / 150.0))
    return (x - jnp.sqrt(ab) * x0_t) / jnp.sqrt(1.0 - ab)


def test_solvers_converge_to_common_limit_with_order():
    """All solvers approach the same ODE solution as steps grow, and the
    2nd-order multistep beats 1st-order Euler at equal low step count."""
    noise = jax.random.normal(jax.random.PRNGKey(3), X0.shape)
    ref = make_sampler("ddim", 500)(curved_denoise, noise)

    def err(kind, steps):
        out = make_sampler(kind, steps)(curved_denoise, noise)
        return float(jnp.abs(out - ref).max())

    # convergence: error shrinks with more steps
    assert err("dpmpp_2m", 50) < err("dpmpp_2m", 10)
    assert err("euler", 50) < err("euler", 10)
    # order: 2nd-order multistep beats Euler at 10 steps
    assert err("dpmpp_2m", 10) < err("euler", 10)
    # all three agree at 50 steps to reasonable tolerance
    assert err("euler", 50) < 0.15 and err("dpmpp_2m", 50) < 0.15


def test_make_sampler_rejects_unknown():
    with pytest.raises(ValueError):
        make_sampler("plms", 10)


def test_pipeline_runs_with_each_sampler():
    """Tiny end-to-end: Text2ImagePipeline under each sampler kind."""
    import dataclasses

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    base = test_config()
    for kind in ("euler", "dpmpp_2m"):
        cfg = base.replace(
            sampler=dataclasses.replace(base.sampler, kind=kind)
        )
        pipe = Text2ImagePipeline(cfg)
        imgs = pipe.generate(["a red lighthouse"], seed=1)
        assert imgs.shape[-1] == 3 and imgs.dtype == np.uint8
        assert np.isfinite(imgs.astype(np.float32)).all()
