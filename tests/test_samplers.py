"""Sampler family tests (ops/samplers.py): Euler and DPM-Solver++(2M)
against the DDIM baseline.

Key correctness property: for the probability-flow ODE with a *consistent*
epsilon field — denoise(x_t, t) returning exactly the eps that places x_t
on the trajectory of a fixed x0 — every solver must recover x0 (the ODE's
solution keeps x0 invariant). This validates coefficients, spacing, and
VP/k-space conversions without any model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.ops.ddim import DDIMSchedule
from cassmantle_tpu.ops.samplers import (
    SAMPLER_KINDS,
    ConsistencySchedule,
    DPMppSchedule,
    EulerSchedule,
    _alpha_bars,
    consistency_boundary,
    consistency_renoise,
    make_consistency_sampler,
    make_sampler,
    make_slot_sampler,
)

X0 = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 3))
AB = jnp.asarray(_alpha_bars().astype(np.float32))


def consistent_denoise(x, t):
    """eps such that x = sqrt(ab)*x0 + sqrt(1-ab)*eps."""
    ab = AB[t]
    return (x - jnp.sqrt(ab) * X0) / jnp.sqrt(1.0 - ab)


@pytest.mark.parametrize("kind", SAMPLER_KINDS)
def test_solver_recovers_x0_under_consistent_field(kind):
    sample = make_sampler(kind, 25)
    noise = jax.random.normal(jax.random.PRNGKey(1), X0.shape)
    out = sample(consistent_denoise, noise)
    np.testing.assert_allclose(np.asarray(out), np.asarray(X0),
                               atol=5e-2, rtol=5e-2)


@pytest.mark.parametrize("kind", ("euler", "dpmpp_2m"))
def test_solver_jits_and_is_deterministic(kind):
    sample = make_sampler(kind, 8)
    noise = jax.random.normal(jax.random.PRNGKey(2), X0.shape)
    f = jax.jit(lambda n: sample(consistent_denoise, n))
    a, b = f(noise), f(noise)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.isfinite(np.asarray(a)).all()


def test_dpmpp_schedule_coefficients_finite():
    s = DPMppSchedule.create(20)
    for name in ("alphas", "sigmas", "c_skip", "c_d0", "c_d1"):
        arr = np.asarray(getattr(s, name))
        assert np.isfinite(arr).all(), name
    # final step: c_skip 0 (sigma_next = 0), first-order (c_d1 = 0)
    assert np.asarray(s.c_skip)[-1] == 0.0
    assert np.asarray(s.c_d1)[-1] == 0.0
    assert np.asarray(s.c_d1)[0] == 0.0  # multistep warmup


def test_dpmpp_2m_interior_coefficients_match_formula():
    """Regression for the 2M correction weight: for an interior step,
    c_d0/c_d1 must equal the DPM-Solver++(2M) formula with weight
    1/(2·r0), r0 = h_prev/h (computed independently here)."""
    steps = 10
    s = DPMppSchedule.create(steps)
    ab = _alpha_bars()
    ts = np.asarray(s.timesteps)
    i = 5  # interior: not warmup, not final
    a = np.sqrt(ab[ts])
    sg = np.sqrt(1.0 - ab[ts])
    lam = np.log(a) - np.log(sg)
    a_next, sg_next = a[i + 1], sg[i + 1]
    lam_next = np.log(a_next) - np.log(sg_next)
    h = lam_next - lam[i]
    h_prev = lam[i] - lam[i - 1]
    r0 = h_prev / h
    em1 = np.expm1(-h)
    w = 1.0 / (2.0 * r0)
    np.testing.assert_allclose(
        float(np.asarray(s.c_d0)[i]), -a_next * em1 * (1.0 + w), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(np.asarray(s.c_d1)[i]), a_next * em1 * w, rtol=1e-5
    )
    np.testing.assert_allclose(
        float(np.asarray(s.c_skip)[i]), sg_next / sg[i], rtol=1e-5
    )


def test_euler_schedule_monotone():
    s = EulerSchedule.create(30)
    sig = np.asarray(s.sigmas)
    assert sig[-1] == 0.0
    assert (np.diff(sig) < 0).all()
    assert len(np.asarray(s.timesteps)) == 30


def curved_denoise(x, t):
    """eps field with t-dependent curvature (the consistent field is exact
    for every solver, so order-of-accuracy needs a curved target)."""
    ab = AB[t]
    x0_t = X0 * (1.0 + 0.3 * jnp.sin(t.astype(jnp.float32) / 150.0))
    return (x - jnp.sqrt(ab) * x0_t) / jnp.sqrt(1.0 - ab)


def test_solvers_converge_to_common_limit_with_order():
    """All solvers approach the same ODE solution as steps grow, and the
    2nd-order multistep beats 1st-order Euler at equal low step count."""
    noise = jax.random.normal(jax.random.PRNGKey(3), X0.shape)
    ref = make_sampler("ddim", 500)(curved_denoise, noise)

    def err(kind, steps):
        out = make_sampler(kind, steps)(curved_denoise, noise)
        return float(jnp.abs(out - ref).max())

    # convergence: error shrinks with more steps
    assert err("dpmpp_2m", 50) < err("dpmpp_2m", 10)
    assert err("euler", 50) < err("euler", 10)
    # order: 2nd-order multistep beats Euler at 10 steps
    assert err("dpmpp_2m", 10) < err("euler", 10)
    # all three agree at 50 steps to reasonable tolerance
    assert err("euler", 50) < 0.15 and err("dpmpp_2m", 50) < 0.15


def test_make_sampler_rejects_unknown():
    with pytest.raises(ValueError):
        make_sampler("plms", 10)


def test_pipeline_runs_with_each_sampler():
    """Tiny end-to-end: Text2ImagePipeline under each sampler kind."""
    import dataclasses

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    base = test_config()
    for kind in ("euler", "dpmpp_2m"):
        cfg = base.replace(
            sampler=dataclasses.replace(base.sampler, kind=kind)
        )
        pipe = Text2ImagePipeline(cfg)
        imgs = pipe.generate(["a red lighthouse"], seed=1)
        assert imgs.shape[-1] == 3 and imgs.dtype == np.uint8
        assert np.isfinite(imgs.astype(np.float32)).all()


# -- few-step consistency sampling (ISSUE 15) --------------------------------


def test_consistency_boundary_condition_at_sigma_min():
    """f(x, σ_min) = x EXACTLY: c_skip(σ_min) = 1 and c_out(σ_min) = 0
    — the boundary condition that makes the parameterization a
    consistency function. Away from the boundary both coefficients are
    strictly interior."""
    ab0 = _alpha_bars()[0]
    sigma_min = float(np.sqrt((1.0 - ab0) / ab0))
    c_skip, c_out = consistency_boundary(sigma_min, sigma_min)
    assert float(c_skip) == 1.0
    assert float(c_out) == 0.0
    c_skip, c_out = consistency_boundary(10.0 * sigma_min, sigma_min)
    assert 0.0 < float(c_skip) < 1.0 and float(c_out) > 0.0


@pytest.mark.parametrize("n", [1, 4, 8])
def test_consistency_schedule_trailing_spacing(n):
    """Grid alignment + trailing spacing: EVERY evaluation timestep is
    a point of the teacher solver discretization — the same
    ``strided_timesteps(teacher_steps)`` grid
    ``ConsistencyDistillTrainer`` trains on, so a really-distilled
    student is never queried at a noise level it never saw — the first
    f-eval sits at the grid's NOISIEST trained point and the last
    strictly above t=0 (the final UNet forward is a real prediction,
    never the boundary identity), with exactly ``n`` evaluation steps
    (the step-count accounting the `pipeline.consistency_steps` counter
    multiplies by) and a terminal re-noise target of ᾱ = 1 (the last
    update IS the x0 estimate)."""
    from cassmantle_tpu.ops.ddim import strided_timesteps

    teacher = 50
    s = ConsistencySchedule.create(n, teacher_steps=teacher)
    ts = np.asarray(s.timesteps)
    grid = strided_timesteps(teacher)
    assert len(ts) == n
    # queried points ⊆ the trainer's discretization, t=0 excluded
    assert set(ts.tolist()) <= set(grid[:-1].tolist())
    assert ts[0] == grid[0] and ts[-1] > 0
    assert (np.diff(ts) < 0).all() if n > 1 else True
    assert float(np.asarray(s.alpha_bars_next)[-1]) == 1.0
    for name in ("alpha_bars", "alpha_bars_next", "c_skip", "c_out"):
        assert np.isfinite(np.asarray(getattr(s, name))).all(), name
    # later (cleaner) steps lean more on the identity term
    assert (np.diff(np.asarray(s.c_skip)) > 0).all() if n > 1 else True


def _affine_denoise(x, t):
    """Works for both the scalar-t monolithic contract and the
    vector-t slot contract."""
    t_b = jnp.reshape(t.astype(jnp.float32), (-1,) + (1,) * (x.ndim - 1))
    return 0.1 * x + 0.01 * t_b


def test_consistency_sample_matches_reference_loop():
    """The scan executes EXACTLY num_steps f-evaluations at the
    schedule's timesteps with the boundary-parameterized update and the
    deterministic re-noise ladder — pinned against a hand-rolled host
    loop using the same published pieces (schedule arrays +
    consistency_renoise)."""
    n = 4
    s = ConsistencySchedule.create(n)
    lat = jax.random.normal(jax.random.PRNGKey(2), (2, 8, 8, 4))
    out = make_consistency_sampler(n)(_affine_denoise, lat)

    x = lat
    for i in range(n):
        t = s.timesteps[i]
        eps = _affine_denoise(x, t)
        ab = s.alpha_bars[i]
        x0 = (x - jnp.sqrt(1.0 - ab) * eps) / jnp.sqrt(ab)
        f = s.c_skip[i] * x + s.c_out[i] * x0
        noise = consistency_renoise(t, x.shape[1:], x.dtype)
        x = jnp.sqrt(s.alpha_bars_next[i]) * f + \
            jnp.sqrt(1.0 - s.alpha_bars_next[i]) * noise
    np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                               atol=1e-5, rtol=1e-5)


def test_consistency_sample_deterministic_and_ignores_rng():
    sample = make_consistency_sampler(4)
    lat = jax.random.normal(jax.random.PRNGKey(5), (1, 8, 8, 4))
    a = sample(_affine_denoise, lat)
    b = sample(_affine_denoise, lat, rng=jax.random.PRNGKey(99))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.isfinite(np.asarray(a)).all()


def test_consistency_slot_sampler_bit_matches_monolithic():
    """The staged slot variant: a solo trajectory stepped one slot-step
    at a time (jitted, as the staged server dispatches it) is
    bit-identical to the jitted monolithic scan — the property that
    lets few-step requests ride step-level continuous batching."""
    n = 4
    lat = jax.random.normal(jax.random.PRNGKey(7), (1, 8, 8, 4))
    ref = jax.jit(
        lambda l: make_consistency_sampler(n)(_affine_denoise, l))(lat)
    prepare, slot_step, steps = make_slot_sampler("consistency", n)
    assert steps == n
    x, aux = prepare(lat)
    jstep = jax.jit(
        lambda x, aux, idx: slot_step(_affine_denoise, x, aux, idx))
    for i in range(steps):
        x, aux = jstep(x, aux, jnp.array([i]))
    np.testing.assert_array_equal(np.asarray(x), np.asarray(ref))


@pytest.fixture(scope="module")
def teacher_pipe():
    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    return Text2ImagePipeline(test_config())


def _lcm_tiny_cfg(num_steps=2):
    import dataclasses

    from cassmantle_tpu.config import test_config

    base = test_config()
    return base.replace(sampler=dataclasses.replace(
        base.sampler, consistency=True, num_steps=num_steps,
        consistency_teacher_steps=base.sampler.num_steps))


def test_consistency_kill_switch_reverts_bit_exact(teacher_pipe,
                                                   monkeypatch):
    """CASSMANTLE_NO_CONSISTENCY=1 reverts a consistency config to the
    TEACHER path bit-exactly (kind @ consistency_teacher_steps — here
    the module teacher pipe's own schedule), and the
    `pipeline.consistency_steps` counter goes quiet — the pinned
    regression contract of the kill switch."""
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline
    from cassmantle_tpu.utils.logging import metrics

    prompts = ["a quiet harbor at dawn"]
    reference = teacher_pipe.generate(prompts, seed=3)
    monkeypatch.setenv("CASSMANTLE_NO_CONSISTENCY", "1")
    off = Text2ImagePipeline(_lcm_tiny_cfg(),
                             share_params_with=teacher_pipe)
    before = metrics.counter_total("pipeline.consistency_steps")
    out = off.generate(prompts, seed=3)
    np.testing.assert_array_equal(out, reference)
    assert metrics.counter_total("pipeline.consistency_steps") == before
    monkeypatch.delenv("CASSMANTLE_NO_CONSISTENCY")
    on = Text2ImagePipeline(_lcm_tiny_cfg(),
                            share_params_with=teacher_pipe)
    live = on.generate(prompts, seed=3)
    assert not np.array_equal(live, reference)  # few-step path engaged
    assert metrics.counter_total("pipeline.consistency_steps") > before


def test_warmed_consistency_loop_never_recompiles(teacher_pipe):
    """Jit sentinel pinned on the warmed few-step serving loop: a
    second same-bucket generate must hit the jit cache with ZERO new
    compiles (the per-step re-noise fold is internal scan structure,
    never a fresh trace)."""
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline
    from cassmantle_tpu.utils import jit_sentinel

    pipe = Text2ImagePipeline(_lcm_tiny_cfg(),
                              share_params_with=teacher_pipe)
    pipe.generate(["a quiet harbor at dawn"], seed=5)   # warmup compile
    with jit_sentinel.no_new_compiles():
        pipe.generate(["a stormy night at sea"], seed=6)


def test_consistency_config_rejections():
    import dataclasses

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    base = test_config()

    def cfg(**kw):
        return base.replace(sampler=dataclasses.replace(
            base.sampler, consistency=True, **kw))

    with pytest.raises(AssertionError, match="few-step"):
        Text2ImagePipeline(cfg(num_steps=12))
    with pytest.raises(AssertionError, match="deepcache"):
        Text2ImagePipeline(cfg(num_steps=4, deepcache=True))
    with pytest.raises(AssertionError, match="encprop"):
        Text2ImagePipeline(cfg(num_steps=4, encprop=True))
    with pytest.raises(AssertionError, match="eta"):
        Text2ImagePipeline(cfg(num_steps=4, eta=0.5))
    with pytest.raises(AssertionError, match="consistency_teacher_steps"):
        # the teacher grid must be finer than the student schedule —
        # the student only trains on the teacher discretization
        Text2ImagePipeline(cfg(num_steps=4, consistency_teacher_steps=4))


def test_img2img_rejects_consistency(teacher_pipe):
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    pipe = Text2ImagePipeline(_lcm_tiny_cfg(),
                              share_params_with=teacher_pipe)
    imgs = np.zeros((1, 64, 64, 3), dtype=np.uint8)
    with pytest.raises(NotImplementedError, match="consistency"):
        pipe.generate_img2img(imgs, ["a sketch"], strength=0.5)
