"""Overload control plane (ISSUE 13): adaptive admission, priority
tiers, the SLO brownout ladder, and the tier-1 goodput smoke.

Layers covered here:

- AdaptiveLimiter AIMD convergence units on an injectable clock
  (gradient clamp, additive probe, floor/cap, predicted-wait math,
  loop-lag shed);
- BatchingQueue priority-inversion regressions (interactive preempts
  background; the starvation bound keeps background progressing; shed
  order) and the submit-time predicted-late rejection;
- BrownoutLadder trip/recover hysteresis units on an injectable clock,
  the CASSMANTLE_NO_BROWNOUT pin, and the chaos flap lever;
- HTTP contract: /compute_score sheds 503 + COMPUTED Retry-After,
  429s carry the bucket's computed refill time, responses carry
  X-Quality-Degraded while a tier is engaged, /readyz carries the
  overload block, and the hedge path skips peers advertising overload;
- the tier-1 goodput smoke: `bench.py overload_drill` machinery at 2x
  sustained capacity on the CPU geometry — goodput plateaus, accepted
  p99 holds the deadline budget, rejects fail fast with a computed
  Retry-After, and a brownout tier engages AND recovers.
"""

import asyncio
import dataclasses

import pytest
from aiohttp.test_utils import TestClient, TestServer

from cassmantle_tpu import chaos
from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.serving import overload
from cassmantle_tpu.serving.overload import (
    DEFAULT_TIERS,
    AdaptiveLimiter,
    BrownoutLadder,
    BrownoutTier,
    degraded_sampler_cfg,
)
from cassmantle_tpu.serving.queue import (
    PRIORITY_BACKGROUND,
    BatchingQueue,
    OverloadShed,
    QueueFull,
)


@pytest.fixture(autouse=True)
def _reset_overload_globals():
    """The ladder/shed-stamp globals are process-wide (like the chaos
    plan): drop them after every test so a mid-assert failure can never
    leak an engaged tier into another module's pipeline tests."""
    yield
    overload._LADDER = None
    overload._LAST_SHED_T = None


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_limiter(**kw):
    kw.setdefault("target_s", 1.0)
    kw.setdefault("min_limit", 4)
    kw.setdefault("max_limit", 1024)
    kw.setdefault("loop_lag_fn", lambda: 0.0)
    return AdaptiveLimiter("t_overload", **kw)


# -- AdaptiveLimiter units ---------------------------------------------------

def test_limiter_starts_wide_open_and_admits_unloaded():
    """Before any signal the limit is max_limit and the predicted wait
    is 0 — unloaded behavior is exactly the old static bound."""
    lim = make_limiter()
    assert lim.limit() == 1024
    assert lim.predicted_wait_s(100) == 0.0
    assert lim.admit(100, "interactive", deadline_s=0.001) is None


def test_limiter_gradient_decrease_converges_in_one_step():
    """A latency breach clamps the limit toward throughput x target
    (Little's law) in ONE decrease — not log-many cooldowns down from
    max_pending while admitted work burns its deadline budget."""
    clock = FakeClock()
    lim = make_limiter(clock=clock)
    # 8 items served in 0.2s => 40 items/s; target 1.0s => est 40
    lim.observe_batch(wait_s=3.0, service_s=0.2, batch_size=8)
    assert lim.limit() == pytest.approx(40.0)
    # within the cooldown a second breach must NOT decrease again
    lim.observe_batch(wait_s=3.0, service_s=0.2, batch_size=8)
    assert lim.limit() == pytest.approx(40.0)
    # after the cooldown the multiplicative step applies (est is not
    # lower than limit*decrease here)
    clock.advance(2.0)
    lim.observe_batch(wait_s=3.0, service_s=0.2, batch_size=8)
    assert lim.limit() == pytest.approx(40.0 * 0.7)


def test_limiter_additive_increase_and_floor_cap():
    clock = FakeClock()
    lim = make_limiter(clock=clock, min_limit=4)
    # drive to the floor: repeated breaches with tiny throughput
    for _ in range(64):
        clock.advance(2.0)
        lim.observe_batch(wait_s=5.0, service_s=1.0, batch_size=1)
    assert lim.limit() == 4.0
    # healthy traffic probes back up additively, +1 per batch
    for i in range(10):
        lim.observe_batch(wait_s=0.0, service_s=0.1, batch_size=4)
        assert lim.limit() == pytest.approx(4.0 + i + 1)
    # and never exceeds the cap
    for _ in range(3000):
        lim.observe_batch(wait_s=0.0, service_s=0.1, batch_size=4)
    assert lim.limit() == 1024.0


def test_limiter_predicted_wait_and_retry_after():
    lim = make_limiter()
    # 4 items in 0.4s => 0.1 s/item
    lim.observe_batch(wait_s=0.0, service_s=0.4, batch_size=4)
    assert lim.predicted_wait_s(10) == pytest.approx(1.0)
    # Retry-After = predicted wait, floored at 1s
    assert lim.retry_after_s(30) == pytest.approx(3.0)
    assert lim.retry_after_s(1) == 1.0


def test_limiter_rejects_predicted_late_and_sheds_background_first():
    lim = make_limiter(background_fraction=0.5)
    lim.observe_batch(wait_s=0.0, service_s=0.4, batch_size=4)  # .1/item
    # force the limit to its floor (est = 1 item/s * 1s target = 1)
    lim.observe_batch(wait_s=5.0, service_s=1.0, batch_size=1)
    assert lim.limit() == 4.0
    # background sheds at half the limit; interactive still admits
    assert lim.admit(3, PRIORITY_BACKGROUND, None).reason == "background"
    assert lim.admit(3, "interactive", None) is None
    # at the limit interactive sheds too
    assert lim.admit(4, "interactive", None).reason == "overload"
    # predicted-late: deadline shorter than the predicted wait, at a
    # depth the limit itself would still admit
    verdict = lim.admit(2, "interactive", deadline_s=0.05)
    assert verdict is not None and verdict.reason == "predicted_late"


def test_limiter_loop_lag_sheds_background_before_queues():
    lag = [0.0]
    lim = make_limiter(loop_lag_shed_s=0.25, loop_lag_fn=lambda: lag[0])
    assert lim.admit(0, PRIORITY_BACKGROUND, None) is None
    lag[0] = 0.3
    verdict = lim.admit(0, PRIORITY_BACKGROUND, None)
    assert verdict is not None and verdict.reason == "loop_lag"
    # interactive survives moderate lag, sheds only at 4x
    assert lim.admit(0, "interactive", None) is None
    lag[0] = 1.1
    assert lim.admit(0, "interactive", None).reason == "loop_lag"


# -- queue priority + admission ----------------------------------------------

@pytest.mark.asyncio
async def test_interactive_preempts_background_in_dispatch_order():
    """Background items queued FIRST must still dispatch after the
    interactive ones (and ride later batches), not starve them."""
    order = []

    def handler(items):
        order.append(list(items))
        return items

    q = BatchingQueue(handler, max_batch=2, max_delay_ms=5,
                      name="t_prio")
    # park the collector so both tiers fill before any dispatch
    q.start()
    await q.stop()
    q._task = object()
    bg = [asyncio.ensure_future(
        q.submit(f"bg{i}", priority=PRIORITY_BACKGROUND))
        for i in range(2)]
    await asyncio.sleep(0)   # let submits enqueue
    ia = [asyncio.ensure_future(q.submit(f"ia{i}")) for i in range(2)]
    await asyncio.sleep(0)
    q._task = None
    q.start()
    await asyncio.gather(*bg, *ia)
    flat = [x for batch in order for x in batch]
    assert flat.index("ia0") < flat.index("bg0"), flat
    assert flat.index("ia1") < flat.index("bg1"), flat
    await q.stop()


@pytest.mark.asyncio
async def test_starvation_bound_keeps_background_progressing():
    """Under sustained interactive load, a pending background item
    heads a batch after at most ``background_every`` consecutive
    interactive batches — rounds keep rotating (ISSUE 13)."""
    order = []

    def handler(items):
        order.append(list(items))
        return items

    q = BatchingQueue(handler, max_batch=1, max_delay_ms=1,
                      name="t_starve", background_every=3)
    # park the collector; enqueue one background item UNDER a deep
    # interactive backlog
    q.start()
    await q.stop()
    q._task = object()
    bg_fut = asyncio.ensure_future(
        q.submit("bg0", priority=PRIORITY_BACKGROUND))
    await asyncio.sleep(0)
    ia = [asyncio.ensure_future(q.submit(f"ia{i}")) for i in range(10)]
    await asyncio.sleep(0)
    q._task = None
    q.start()
    await asyncio.wait_for(bg_fut, timeout=10.0)
    await asyncio.gather(*ia)
    # the background item dispatched within the bound, not at the tail
    bg_at = next(i for i, b in enumerate(order) if "bg0" in b)
    assert bg_at <= 3, order[:bg_at + 1]
    # and interactive work was never starved by it: everything served
    assert sum(len(b) for b in order) == 11
    await q.stop()


@pytest.mark.asyncio
async def test_submit_rejects_predicted_late_with_computed_retry_after():
    """A submission whose predicted wait already exceeds its deadline
    fails AT SUBMIT (fast) with the computed Retry-After — it never
    sits in the queue burning its budget."""
    import time as _time

    lim = make_limiter()
    lim.observe_batch(wait_s=0.0, service_s=1.0, batch_size=1)  # 1 s/item
    q = BatchingQueue(lambda items: items, max_batch=8, max_delay_ms=1,
                      name="t_predlate", admission=lim)
    q.start()
    await q.stop()
    q._task = object()               # park: keep depth in the queue
    loop = asyncio.get_running_loop()
    for i in range(4):
        q._queue.put_nowait((i, loop.create_future()))
    t0 = _time.monotonic()
    with pytest.raises(OverloadShed) as exc:
        await q.submit("late", deadline_s=0.5)
    assert _time.monotonic() - t0 < 0.05
    assert exc.value.reason == "predicted_late"
    assert exc.value.retry_after_s >= 1.0
    q._task = None
    await q.stop()


@pytest.mark.asyncio
async def test_overload_shed_is_queue_full_and_counts():
    """OverloadShed subclasses QueueFull (legacy degrade paths keep
    working) and the adaptive limit rejection carries Retry-After."""
    assert issubclass(OverloadShed, QueueFull)
    lim = make_limiter(min_limit=1)
    # force a tiny limit
    lim.observe_batch(wait_s=10.0, service_s=1.0, batch_size=1)
    q = BatchingQueue(lambda items: items, max_batch=8, max_delay_ms=1,
                      name="t_shed", admission=lim)
    q.start()
    await q.stop()
    q._task = object()
    loop = asyncio.get_running_loop()
    for i in range(int(lim.limit()) + 1):
        q._queue.put_nowait((i, loop.create_future()))
    with pytest.raises(OverloadShed) as exc:
        await q.submit("x")
    assert exc.value.reason == "overload"
    q._task = None
    await q.stop()


@pytest.mark.asyncio
async def test_chaos_server_admit_forces_shed():
    """The ``server.admit`` fault point (docs/CHAOS.md): a fired rule
    sheds the request with reason ``chaos`` and a Retry-After — the
    drill lever for mis-admission."""
    chaos.configure("server.admit=raise:times=1")
    try:
        q = BatchingQueue(lambda items: items, max_batch=4,
                          max_delay_ms=1, name="t_chaosadmit")
        with pytest.raises(OverloadShed) as exc:
            await q.submit("x")
        assert exc.value.reason == "chaos"
        # rule exhausted (times=1): the next submit serves normally
        assert await q.submit("y") == "y"
        await q.stop()
    finally:
        chaos.disarm()


# -- brownout ladder units ---------------------------------------------------

def make_ladder(clock, **kw):
    kw.setdefault("step_up_dwell_s", 1.0)
    kw.setdefault("step_down_dwell_s", 3.0)
    return BrownoutLadder(DEFAULT_TIERS, clock=clock, **kw)


def burn(name="score_latency", state="burning"):
    return {name: {"state": state, "fast_burn": 5.0, "slow_burn": 2.0}}


def ok(name="score_latency"):
    return {name: {"state": "ok", "fast_burn": 0.1, "slow_burn": 0.2}}


def test_brownout_trips_after_dwell_and_steps_per_dwell(monkeypatch):
    monkeypatch.delenv("CASSMANTLE_NO_BROWNOUT", raising=False)
    clock = FakeClock()
    ladder = make_ladder(clock)
    ladder.on_slo_eval(burn())
    assert ladder.tier() == 0          # dwell not yet served
    clock.advance(1.1)
    ladder.on_slo_eval(burn())
    assert ladder.tier() == 1          # sustained burn -> tier 1
    ladder.on_slo_eval(burn())
    assert ladder.tier() == 1          # each rung re-earns its dwell
    clock.advance(1.1)
    ladder.on_slo_eval(burn())
    assert ladder.tier() == 2


def test_brownout_recovers_with_hysteresis(monkeypatch):
    monkeypatch.delenv("CASSMANTLE_NO_BROWNOUT", raising=False)
    clock = FakeClock()
    ladder = make_ladder(clock)
    ladder.on_slo_eval(burn())       # arms the burn dwell
    for _ in range(2):
        clock.advance(1.1)
        ladder.on_slo_eval(burn())
    assert ladder.tier() == 2
    # recovery must DWELL: an immediate ok does not step down
    ladder.on_slo_eval(ok())
    assert ladder.tier() == 2
    clock.advance(3.1)
    ladder.on_slo_eval(ok())
    assert ladder.tier() == 1          # one rung per dwell, not a cliff
    # a burn mid-recovery resets the ok-dwell (hysteresis, no flap)
    clock.advance(1.5)
    ladder.on_slo_eval(burn())
    clock.advance(1.5)
    ladder.on_slo_eval(ok())
    assert ladder.tier() == 1
    clock.advance(3.1)
    ladder.on_slo_eval(ok())
    assert ladder.tier() == 0


def test_brownout_watches_only_configured_objectives(monkeypatch):
    monkeypatch.delenv("CASSMANTLE_NO_BROWNOUT", raising=False)
    clock = FakeClock()
    ladder = make_ladder(clock, objectives=("score_latency",))
    clock.advance(1.1)
    ladder.on_slo_eval(burn("replication_lag"))
    clock.advance(1.1)
    ladder.on_slo_eval(burn("replication_lag"))
    assert ladder.tier() == 0          # unwatched objective: no tiers


def test_brownout_kill_switch_pins_tier_zero(monkeypatch):
    clock = FakeClock()
    ladder = make_ladder(clock)
    for _ in range(3):
        clock.advance(1.1)
        ladder.on_slo_eval(burn())
    assert ladder.tier() >= 2
    monkeypatch.setenv("CASSMANTLE_NO_BROWNOUT", "1")
    assert ladder.tier() == 0          # pinned immediately on read
    ladder.on_slo_eval(burn())
    assert ladder.status()["tier"] == 0 and ladder.status()["disabled"]
    monkeypatch.delenv("CASSMANTLE_NO_BROWNOUT")


def test_chaos_brownout_forces_tier_flap(monkeypatch):
    """The ``overload.brownout`` fault point steps the tier up without
    any SLO burn — composed with recovery this drills tier flapping."""
    monkeypatch.delenv("CASSMANTLE_NO_BROWNOUT", raising=False)
    clock = FakeClock()
    ladder = make_ladder(clock)
    chaos.configure("overload.brownout=raise:times=2")
    try:
        ladder.on_slo_eval(ok())
        assert ladder.tier() == 1
        ladder.on_slo_eval(ok())
        assert ladder.tier() == 2
        # rule exhausted: normal recovery takes over
        clock.advance(3.1)
        ladder.on_slo_eval(ok())
        clock.advance(0.1)
        ladder.on_slo_eval(ok())
        assert ladder.tier() == 2      # ok-dwell restarted post-chaos
        clock.advance(3.1)
        ladder.on_slo_eval(ok())
        assert ladder.tier() == 1
    finally:
        chaos.disarm()


def test_degraded_sampler_cfg_respects_invariants():
    cfg = _tiny_config()
    s = dataclasses.replace(cfg.sampler, num_steps=50, deepcache=True,
                            image_size=512)
    tier = BrownoutTier("t", num_steps_scale=0.6, image_size_scale=0.5)
    d = degraded_sampler_cfg(s, tier)
    assert d.num_steps == 30 and d.num_steps % 2 == 0
    assert d.image_size == 256 and d.image_size % 16 == 0
    # encprop stride only moves when encprop is on
    tier2 = BrownoutTier("t2", encprop_stride_add=2)
    assert degraded_sampler_cfg(s, tier2).encprop_stride == \
        s.encprop_stride
    s_ep = dataclasses.replace(s, deepcache=False, encprop=True,
                               encprop_stride=3)
    assert degraded_sampler_cfg(s_ep, tier2).encprop_stride == 5
    # the identity tier is a no-op config (callers skip the degraded
    # path => tier 0 is bit-for-bit the old behavior)
    assert degraded_sampler_cfg(s, BrownoutTier("full")) == s


def test_degraded_sampler_cfg_few_step_tier(monkeypatch):
    """The few-step tier swaps the sampling loop for the consistency
    student at 4 steps, clears the non-composing deepcache/encprop
    flags, carries the resolution delta of later rungs, ONLY engages
    when the deployment declares a distilled student checkpoint
    (consistency_available — an undistilled eps-net sampled 4-step is
    near-noise), and defers to the CASSMANTLE_NO_CONSISTENCY kill
    switch (degrading the TEACHER schedule instead)."""
    monkeypatch.delenv("CASSMANTLE_NO_CONSISTENCY", raising=False)
    from cassmantle_tpu.serving.overload import (
        CONSISTENCY_BROWNOUT_STEPS,
    )

    cfg = _tiny_config()
    # a stock (undistilled) deployment: the few-step delta must NOT
    # engage — the rung degrades like the previous one instead
    stock = dataclasses.replace(cfg.sampler, num_steps=50,
                                image_size=512)
    d_stock = degraded_sampler_cfg(
        stock, BrownoutTier("t", num_steps_scale=0.6, consistency=True))
    assert not d_stock.consistency and d_stock.num_steps == 30
    s = dataclasses.replace(cfg.sampler, num_steps=50, encprop=True,
                            encprop_stride=3, image_size=512,
                            consistency_available=True)
    tier = BrownoutTier("t", num_steps_scale=0.6, consistency=True)
    d = degraded_sampler_cfg(s, tier)
    assert d.consistency and d.num_steps == CONSISTENCY_BROWNOUT_STEPS
    assert not d.deepcache and not d.encprop
    assert d.image_size == 512                    # few-step BEFORE low-res
    low = BrownoutTier("t2", consistency=True, image_size_scale=0.5)
    assert degraded_sampler_cfg(s, low).image_size == 256
    # a config already serving the student keeps its step count
    s_lcm = dataclasses.replace(cfg.sampler, consistency=True,
                                num_steps=2)
    assert degraded_sampler_cfg(s_lcm, tier).num_steps == 2
    # kill switch: the tier degrades the teacher path instead
    monkeypatch.setenv("CASSMANTLE_NO_CONSISTENCY", "1")
    d_off = degraded_sampler_cfg(s, tier)
    assert not d_off.consistency and d_off.num_steps == 30
    s_lcm4 = dataclasses.replace(cfg.sampler, consistency=True,
                                 num_steps=4,
                                 consistency_teacher_steps=50)
    d_off2 = degraded_sampler_cfg(s_lcm4, tier)
    assert not d_off2.consistency and d_off2.num_steps == 30


def test_peer_advert_reflects_shed_and_tier(monkeypatch):
    monkeypatch.delenv("CASSMANTLE_NO_BROWNOUT", raising=False)
    overload._LAST_SHED_T = None
    assert "shed" not in overload.peer_advert()
    overload.note_shed()
    assert overload.peer_advert().get("shed") == 1
    overload._LAST_SHED_T = None


# -- brownout actuation ------------------------------------------------------

def test_pipeline_actuates_brownout_tier_and_reverts_bit_exact(
        monkeypatch):
    """The tier-keyed degraded sampler: a resolution/step tier changes
    the served image (smaller, fewer steps), each engaged delta
    compiles ONCE (cached by key), and tier 0 returns the untouched
    default path — bit-for-bit the pre-brownout output."""
    monkeypatch.delenv("CASSMANTLE_NO_BROWNOUT", raising=False)
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    cfg = _tiny_config()
    pipe = Text2ImagePipeline(cfg)
    full = pipe.generate(["a storm rolls in"], seed=1)
    assert full.shape[1] == cfg.sampler.image_size
    clock = FakeClock()
    ladder = make_ladder(clock)
    monkeypatch.setattr(overload, "_LADDER", ladder)
    with ladder._lock:
        ladder._step_to(4, "test")  # low-res: few-step student, size x0.5
    degraded = pipe.generate(["a storm rolls in"], seed=1)
    assert degraded.shape[1] == max(32, cfg.sampler.image_size // 2)
    assert len(pipe._tier_fns) == 1
    pipe.generate(["a storm rolls in"], seed=1)
    assert len(pipe._tier_fns) == 1     # same delta -> cached variant
    with ladder._lock:
        ladder._step_to(0, "test")
    back = pipe.generate(["a storm rolls in"], seed=1)
    assert (back == full).all()         # tier 0 = the old path, bitwise


@pytest.mark.asyncio
async def test_fake_backend_and_blur_ladder_honor_tiers(monkeypatch):
    monkeypatch.delenv("CASSMANTLE_NO_BROWNOUT", raising=False)
    from cassmantle_tpu.engine.content import FakeContentBackend

    clock = FakeClock()
    ladder = make_ladder(clock)
    monkeypatch.setattr(overload, "_LADDER", ladder)
    backend = FakeContentBackend(image_size=64)
    content = await backend.generate("seed", True)
    assert content.image.shape[0] == 64
    assert overload.blur_bucket_px() == 0.5
    with ladder._lock:
        ladder._step_to(5, "test")      # coarse-blur tier: all deltas
    content = await backend.generate("seed", True)
    assert content.image.shape[0] == 32
    assert overload.blur_bucket_px() == 2.0
    with ladder._lock:
        ladder._step_to(0, "test")


def test_blur_quantize_coarse_tiers_round_up_only(monkeypatch):
    """Review regression: the coarse-blur tier must only ever ADD
    blur. At the default quantum the legacy round-to-nearest buckets
    are bit-for-bit; a coarsened quantum rounds UP, so a near-winner's
    0.9 px reveal radius becomes a 2.0 px bucket — never the SHARP
    0.0 bucket nearest-rounding would have served."""
    monkeypatch.delenv("CASSMANTLE_NO_BROWNOUT", raising=False)
    from cassmantle_tpu.serving.overload import quantize_blur_radius

    monkeypatch.setattr(overload, "_LADDER", None)
    assert quantize_blur_radius(0.6) == 0.5     # legacy nearest
    assert quantize_blur_radius(0.2) == 0.0     # legacy sharp zone
    clock = FakeClock()
    ladder = make_ladder(clock)
    monkeypatch.setattr(overload, "_LADDER", ladder)
    with ladder._lock:
        ladder._step_to(5, "test")              # quantum 2.0 px
    assert quantize_blur_radius(0.9) == 2.0     # up, not down to sharp
    assert quantize_blur_radius(2.1) == 4.0
    assert quantize_blur_radius(0.0) == 0.0     # a true winner stays sharp
    with ladder._lock:
        ladder._step_to(0, "test")


@pytest.mark.asyncio
async def test_combined_priority_depth_bounded_at_max_pending():
    """Review regression: two priority tiers must not quietly double
    the static max_pending wall — the COMBINED depth is bounded."""
    q = BatchingQueue(lambda items: items, max_batch=1, max_delay_ms=1,
                      max_pending=2, name="t_combined")
    q.start()
    await q.stop()
    q._task = object()
    loop = asyncio.get_running_loop()
    q._queue.put_nowait((0, loop.create_future()))
    q._bg_queue.put_nowait((1, loop.create_future()))
    with pytest.raises(QueueFull):
        await q.submit(2)
    with pytest.raises(QueueFull):
        await q.submit(3, priority=PRIORITY_BACKGROUND)
    q._task = None
    await q.stop()


def test_transient_limiter_not_registered_in_status_block():
    """Review regression: constructing a limiter (config probes, lock
    tests) must not leak a phantom queue row into /readyz; only
    make_admission-wired limiters register."""
    AdaptiveLimiter("t_phantom_probe")
    assert "t_phantom_probe" not in overload.status_block()["queues"]
    from cassmantle_tpu.serving.overload import make_admission

    lim = make_admission("t_wired_probe", _tiny_config())
    assert lim is not None
    assert "t_wired_probe" in overload.status_block()["queues"]
    del overload._LIMITERS["t_wired_probe"]


# -- rate-limit Retry-After (satellite) --------------------------------------

def test_rate_limit_retry_after_computed_from_refill():
    from cassmantle_tpu.server.ratelimit import RateLimiter, TokenBucket

    bucket = TokenBucket(rate=2.0)
    while bucket.allow():
        pass
    # <1 token left at 2 tokens/s: refill to one token takes <= 0.5s
    ra = bucket.retry_after_s()
    assert 0.0 < ra <= 0.5
    limiter = RateLimiter()
    principal = (("1.2.3.4", "lobby"))
    assert limiter.allow(principal, "/compute_score", 1.0)
    assert not limiter.allow(principal, "/compute_score", 1.0)
    assert 0.0 < limiter.retry_after_s(principal, "/compute_score") <= 1.0
    # unknown bucket (evicted): 0, caller floors the header at 1
    assert limiter.retry_after_s(("9.9.9.9", "x"), "/y") == 0.0


# -- HTTP contract -----------------------------------------------------------

def _drill_cfg(batch_ms=40.0):
    cfg = _tiny_config()
    return cfg.replace(
        game=dataclasses.replace(cfg.game, time_per_prompt=30.0,
                                 rate_limit_default=1e6,
                                 rate_limit_api=1e6),
        serving=dataclasses.replace(
            cfg.serving, fake_score_batch_ms=batch_ms,
            score_batch_sizes=(4,), max_queue_delay_ms=2.0,
            submit_deadline_s=1.0, queue_latency_target_s=0.2,
            admission_min_pending=2, loop_lag_shed_s=10.0),
    )


async def _fabric_client(cfg):
    from cassmantle_tpu.server.app import build_fabric, create_app

    fabric = build_fabric(cfg, fake=True)
    app = create_app(fabric, cfg, start_timer=False)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client, fabric


@pytest.mark.asyncio
async def test_compute_score_sheds_503_with_computed_retry_after():
    """The client-visible overload contract: a shed /compute_score is
    503 + computed Retry-After + X-Overload-Shed, answered fast."""
    import time as _time

    client, _ = await _fabric_client(_drill_cfg())
    try:
        await client.get("/init?session=s1")
        res = await client.get("/fetch/contents?session=s1")
        masks = (await res.json())["prompt"]["masks"] or [0]
        guess = {"inputs": {str(masks[0]): "w"}}
        # arm AFTER warmup: the fault point must fire on OUR submit
        chaos.configure("server.admit=raise:times=1")
        try:
            t0 = _time.monotonic()
            res = await client.post("/compute_score?session=s1",
                                    json=guess)
            elapsed = _time.monotonic() - t0
            assert res.status == 503
            assert int(res.headers["Retry-After"]) >= 1
            assert res.headers["X-Overload-Shed"] == "chaos"
            assert elapsed < 0.5     # no queueing, no deadline burn
            # next request is admitted and served normally
            res = await client.post("/compute_score?session=s1",
                                    json=guess)
            assert res.status == 200
        finally:
            chaos.disarm()
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_quality_degraded_header_and_readyz_overload_block():
    client, fabric = await _fabric_client(_drill_cfg())
    try:
        res = await client.get("/readyz")
        block = (await res.json())["overload"]
        assert block["brownout"]["tier"] == 0
        assert "score" in block["queues"]
        assert "limit" in block["queues"]["score"]
        res = await client.get("/init")
        assert "X-Quality-Degraded" not in res.headers
        # engage a tier directly on the live ladder
        ladder = overload.ladder()
        with ladder._lock:
            ladder._step_to(2, "test")
        res = await client.get("/init")
        assert res.headers["X-Quality-Degraded"] == "tier-2"
        res = await client.get("/readyz")
        block = (await res.json())["overload"]
        assert block["brownout"]["tier"] == 2
        with ladder._lock:
            ladder._step_to(0, "test")
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_hedge_skips_peer_advertising_overload():
    """A peer whose heartbeat advertises shedding must not be hedged
    into (counted score.hedge_skipped_overloaded); with no other peer
    the ladder bottoms out at marked floor scores."""
    from cassmantle_tpu.utils.logging import metrics

    client, fabric = await _fabric_client(_drill_cfg(batch_ms=0.0))
    try:
        await client.get("/init?session=s1")

        async def table():
            return {
                fabric.worker_id: {"info": {"addr": ""}, "stale": False,
                                   "age_s": 0.0},
                "sick-peer": {
                    "info": {"addr": "http://127.0.0.1:1",
                             "shed": 1},
                    "stale": False, "age_s": 0.0},
            }

        fabric.membership.table = table
        breaker = fabric.supervisor.score_breaker
        for _ in range(breaker.failure_threshold):
            breaker.record_failure()
        before = metrics.counter_total("score.hedge_skipped_overloaded")
        attempts = metrics.counter_total("score.hedge_attempts")
        res = await client.post("/compute_score?session=s1",
                                json={"inputs": {"0": "w"}})
        assert res.status == 200
        assert res.headers["X-Score-Degraded"] == "floor"
        assert metrics.counter_total(
            "score.hedge_skipped_overloaded") == before + 1
        # the sick peer was never dialed
        assert metrics.counter_total("score.hedge_attempts") == attempts
        breaker.record_success()
    finally:
        await client.close()


# -- the tier-1 goodput smoke (acceptance) -----------------------------------

def test_overload_drill_goodput_plateaus_and_brownout_cycles():
    """ISSUE 13 acceptance on the CPU smoke geometry: at 2x sustained
    capacity through the real fabric, goodput plateaus (>= 60% of the
    known single-arm capacity and >= the baseline phase's goodput),
    accepted p99 stays inside the deadline budget (1.5s), rejected
    requests fail fast with a computed Retry-After >= 1s, and at least
    one brownout tier engages under burn and steps back down by drill
    end (hysteresis observed end to end)."""
    from bench import overload_drill_run

    raw = overload_drill_run(batch_ms=100.0, bucket=4, base_port=8581,
                             baseline_s=2.5, overload_s=4.0,
                             recovery_s=4.5)
    phases = raw["phases"]
    base, over = phases["baseline"], phases["overload"]
    capacity = raw["capacity_per_s"]
    # plateau, not collapse: the 2x phase keeps serving at capacity
    # scale (0.6 leaves headroom for container CPU jitter; collapse
    # looks like ~0 goodput with every request expiring at deadline)
    assert over["goodput_per_s"] >= 0.6 * capacity, raw
    assert over["goodput_per_s"] >= base["goodput_per_s"], raw
    assert over["errors"] == 0, raw
    # accepted work keeps its latency contract (deadline budget 1.5s)
    accepted_p99 = sorted(over["accepted_ms"])[
        int(len(over["accepted_ms"]) * 0.99) - 1]
    assert accepted_p99 <= 1500.0, accepted_p99
    # rejected work fails fast with the computed Retry-After
    assert over["rejected_ms"], "2x load produced no rejections"
    rejected_p50 = sorted(over["rejected_ms"])[
        len(over["rejected_ms"]) // 2]
    assert rejected_p50 < 100.0, rejected_p50
    assert over["retry_after_s"] and min(over["retry_after_s"]) >= 1.0
    # the brownout ladder engaged under burn and recovered (hysteresis)
    assert over["max_tier"] >= 1.0, raw
    assert raw["final_tier"] < over["max_tier"], raw
    # /readyz carried the overload block throughout
    assert "brownout" in raw["overload_block"]


def test_no_brownout_env_keeps_drill_at_tier_zero(monkeypatch):
    """CASSMANTLE_NO_BROWNOUT pins tier 0 through the whole stack: the
    ladder ignores burn, no header, gauge stays 0. (The unloaded
    bit-for-bit contract is the tier-0 default path — pinned by the
    degraded_sampler_cfg identity test above and by every pre-existing
    serving test running at tier 0.)"""
    monkeypatch.setenv("CASSMANTLE_NO_BROWNOUT", "1")
    clock = FakeClock()
    ladder = make_ladder(clock)
    for _ in range(4):
        clock.advance(2.0)
        ladder.on_slo_eval(burn())
    assert ladder.tier() == 0
    assert ladder.status()["disabled"] is True
