"""Chaos fabric core (ISSUE 12): seeded plans, fault-point semantics,
the zero-overhead disarmed contract, the /readyz chaos block, and the
fault-point registry lint (docs/CHAOS.md)."""

import asyncio
import threading
import time

import pytest

from cassmantle_tpu import chaos
from cassmantle_tpu.chaos import ChaosInjected, ChaosPartition


@pytest.fixture(autouse=True)
def _disarm_chaos():
    """Every test leaves the process-global plan disarmed."""
    chaos.disarm()
    yield
    chaos.disarm()


# -- spec parsing ----------------------------------------------------------

def test_parse_spec_grammar():
    seed, rules = chaos.parse_spec(
        "seed=9;round.generate=flake:p=0.25;"
        "store.client.op=latency:delay_s=0.02,p=0.3;"
        "fabric.peer_http=partition:peer=w-b;"
        "queue.dispatch=wedge:after=3,times=1,wedge_s=2.5")
    assert seed == 9
    by_point = {r.point: r for r in rules}
    assert by_point["round.generate"].kind == "flake"
    assert by_point["round.generate"].p == 0.25
    assert by_point["store.client.op"].delay_s == 0.02
    assert by_point["fabric.peer_http"].peer == "w-b"
    w = by_point["queue.dispatch"]
    assert (w.after, w.times, w.wedge_s) == (3, 1, 2.5)


def test_parse_spec_rejects_typos_loudly():
    """A typo'd drill must fail at arm time, not inject nothing."""
    with pytest.raises(ValueError, match="unknown fault point"):
        chaos.parse_spec("round.generat=raise")
    with pytest.raises(ValueError, match="unknown kind"):
        chaos.parse_spec("round.generate=explode")
    with pytest.raises(ValueError, match="unknown param"):
        chaos.parse_spec("round.generate=raise:bogus=1")
    with pytest.raises(ValueError):
        chaos.parse_spec("just-a-token")


def test_flake_defaults_to_half_probability():
    _, rules = chaos.parse_spec("round.generate=flake")
    assert rules[0].p == 0.5


# -- seeded determinism (acceptance) ---------------------------------------

def _drive(plan, point, n=30, peer=None):
    for _ in range(n):
        try:
            plan.hit(point, peer)
        except (ChaosInjected, ChaosPartition):
            pass
    return [(f["point"], f["hit"]) for f in plan.schedule()]


def test_same_seed_replays_identical_schedule():
    spec = "seed=5;round.generate=flake:p=0.4"
    a = _drive(chaos.configure(spec), "round.generate")
    chaos.disarm()
    b = _drive(chaos.configure(spec), "round.generate")
    assert a == b and a, "same seed must replay the same fault schedule"
    chaos.disarm()
    c = _drive(chaos.configure("seed=6;round.generate=flake:p=0.4"),
               "round.generate")
    assert a != c


def test_schedule_is_independent_across_points():
    """A point's fire/skip pattern is a pure function of ITS hit
    sequence: interleaving hits to another point must not perturb it."""
    spec = ("seed=3;round.generate=flake:p=0.4;"
            "fabric.heartbeat=flake:p=0.4")
    plan = chaos.configure(spec)
    solo = _drive(plan, "round.generate")
    chaos.disarm()
    plan = chaos.configure(spec)
    for i in range(30):
        for point in ("fabric.heartbeat", "round.generate"):
            try:
                plan.hit(point)
            except ChaosInjected:
                pass
    interleaved = [(f["point"], f["hit"]) for f in plan.schedule()
                   if f["point"] == "round.generate"]
    assert interleaved == solo


# -- kind semantics --------------------------------------------------------

def test_raise_after_times_and_peer_scoping():
    plan = chaos.configure(
        "seed=1;fabric.peer_http=partition:peer=w-b,after=1,times=2")
    # wrong peer never consumes the schedule
    plan.hit("fabric.peer_http", peer="w-a")
    plan.hit("fabric.peer_http", peer="w-b")        # after=1: skipped
    with pytest.raises(ChaosPartition) as exc:
        plan.hit("fabric.peer_http", peer="w-b")
    assert isinstance(exc.value, ConnectionError)   # failover paths engage
    with pytest.raises(ChaosPartition):
        plan.hit("fabric.peer_http", peer="w-b")
    plan.hit("fabric.peer_http", peer="w-b")        # times=2 exhausted
    assert len(plan.schedule()) == 2


def test_latency_uses_injectable_sleep():
    slept = []
    chaos.configure("seed=1;store.client.op=latency:delay_s=0.25",
                    sleep=slept.append)
    chaos.fault_point("store.client.op")
    assert slept == [0.25]


def test_async_latency_and_raise():
    chaos.configure("seed=1;round.generate=latency:delay_s=0.0,times=1;"
                    "round.generate=raise:times=1")

    async def run():
        await chaos.afault_point("round.generate")   # latency, returns
        with pytest.raises(ChaosInjected):
            await chaos.afault_point("round.generate")
        await chaos.afault_point("round.generate")   # both exhausted

    asyncio.run(run())


def test_wedge_blocks_until_released():
    chaos.configure("seed=1;queue.dispatch=wedge:times=1,wedge_s=10")
    entered = threading.Event()
    done = threading.Event()

    def wedged():
        entered.set()
        chaos.fault_point("queue.dispatch")
        done.set()

    t = threading.Thread(target=wedged, daemon=True)
    t.start()
    assert entered.wait(1.0)
    assert not done.wait(0.2), "wedge must hold until released"
    assert chaos.release("queue.dispatch") == 1
    assert done.wait(2.0), "release must unblock the wedge"
    t.join(timeout=2.0)


# -- the zero-overhead disarmed contract (acceptance) ----------------------

def test_disarmed_fault_point_is_a_noop_with_no_measurable_work():
    assert not chaos.armed()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        chaos.fault_point("round.generate")
    elapsed = time.perf_counter() - t0
    # one module-global None check per call: generous bound is 5µs/call
    # even on a loaded 2-core CI host (measured ~0.1µs)
    assert elapsed < 1.0, f"{n} disarmed calls took {elapsed:.2f}s"
    # the async form allocates NO coroutine while disarmed: it returns
    # one shared done-awaitable (identity-pinned so a refactor can't
    # silently reintroduce per-call allocation)
    assert chaos.afault_point("round.generate") is \
        chaos.afault_point("fabric.heartbeat")


# -- arming surfaces -------------------------------------------------------

def test_configure_from_env_and_config(monkeypatch):
    from cassmantle_tpu.config import ChaosConfig

    monkeypatch.setenv(chaos.CHAOS_ENV,
                       "seed=4;round.generate=raise:times=0")
    plan = chaos.configure_from_env(ChaosConfig(spec=""))
    assert plan is not None and plan.seed == 4
    monkeypatch.delenv(chaos.CHAOS_ENV)
    plan = chaos.configure_from_env(
        ChaosConfig(spec="fabric.heartbeat=raise:times=0", seed=11))
    assert plan is not None and plan.seed == 11
    assert chaos.configure_from_env(ChaosConfig()) is None
    assert not chaos.armed()


@pytest.mark.asyncio
async def test_readyz_and_healthz_carry_chaos_block_when_armed():
    """A drill can never be mistaken for an incident: both probe
    surfaces say a plan is armed, and say nothing when it is not."""
    import dataclasses

    from aiohttp.test_utils import TestClient, TestServer

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.engine.content import (
        FakeContentBackend,
        hash_embed,
        hash_similarity,
    )
    from cassmantle_tpu.engine.game import Game
    from cassmantle_tpu.engine.store import MemoryStore
    from cassmantle_tpu.server.app import create_app

    cfg = test_config()
    cfg = cfg.replace(game=dataclasses.replace(
        cfg.game, rate_limit_default=1e6, rate_limit_api=1e6))
    game = Game(cfg, MemoryStore(), FakeContentBackend(image_size=16),
                hash_embed, hash_similarity)
    client = TestClient(TestServer(create_app(game, cfg,
                                              start_timer=False)))
    await client.start_server()
    try:
        for route in ("/readyz", "/healthz"):
            body = await (await client.get(route)).json()
            sup = body if route == "/readyz" else body["supervisor"]
            assert "chaos" not in sup
        chaos.configure("seed=2;round.generate=raise:times=0")
        for route in ("/readyz", "/healthz"):
            res = await client.get(route)
            assert res.status == 200, "an armed plan is NOT degradation"
            body = await res.json()
            sup = body if route == "/readyz" else body["supervisor"]
            assert sup["chaos"]["armed"] is True
            assert sup["chaos"]["seed"] == 2
    finally:
        await client.close()


def test_create_app_arms_from_config_spec():
    """ChaosConfig.spec arms at app build (CASSMANTLE_CHAOS wins when
    both are set; configure_from_env is covered above)."""
    import dataclasses

    from cassmantle_tpu.config import ChaosConfig, test_config
    from cassmantle_tpu.engine.content import (
        FakeContentBackend,
        hash_embed,
        hash_similarity,
    )
    from cassmantle_tpu.engine.game import Game
    from cassmantle_tpu.engine.store import MemoryStore
    from cassmantle_tpu.server.app import create_app

    cfg = test_config().replace(chaos=ChaosConfig(
        spec="fabric.heartbeat=raise:times=0", seed=13))
    game = Game(cfg, MemoryStore(), FakeContentBackend(image_size=16),
                hash_embed, hash_similarity)
    create_app(game, cfg, start_timer=False)
    assert chaos.armed() and chaos.plan().seed == 13


# -- fault-point registry lint (satellite) ---------------------------------

def _lint(source, **kw):
    from cassmantle_tpu.analysis.core import parse_source, run_passes
    from cassmantle_tpu.analysis.faultpoints import FaultPointPass

    registry = kw.pop("registry", {p: 1 for p in chaos.FAULT_POINTS})
    kw.setdefault("check_orphans", False)   # single-fixture walks
    return run_passes(
        [parse_source(source)],
        [FaultPointPass(registry=registry, **kw)])


def test_faultpoint_lint_flags_unregistered_and_dynamic_names():
    bad = _lint("from cassmantle_tpu.chaos import fault_point\n"
                "def f():\n"
                "    fault_point('no.such.point')\n")
    assert len(bad) == 1 and "no row" in bad[0].message
    dyn = _lint("from cassmantle_tpu.chaos import afault_point\n"
                "async def f(name):\n"
                "    await afault_point(name)\n")
    assert len(dyn) == 1 and "literal" in dyn[0].message
    clean = _lint("from cassmantle_tpu.chaos import fault_point\n"
                  "def f():\n"
                  "    fault_point('round.generate')\n")
    assert clean == []


def test_faultpoint_lint_reports_stale_registry_rows():
    from cassmantle_tpu.analysis.core import parse_source, run_passes
    from cassmantle_tpu.analysis.faultpoints import FaultPointPass

    findings = run_passes(
        [parse_source("x = 1\n")],
        [FaultPointPass(registry={"ghost.point": 7},
                        check_orphans=True)])
    assert len(findings) == 1 and "stale" in findings[0].message
    # scoped runs skip the orphan direction (tools/lint_all.py)
    findings = run_passes(
        [parse_source("x = 1\n")],
        [FaultPointPass(registry={"ghost.point": 7},
                        check_orphans=False)])
    assert findings == []


def test_repo_fault_points_match_docs_registry_and_core_table():
    """Three-way sync: the docs/CHAOS.md registry, the literals wired
    into the package, and chaos.FAULT_POINTS (what plans validate
    against) must all agree — the whole-package lint run is the
    tier-1 gate."""
    from cassmantle_tpu.analysis.core import (
        PACKAGE,
        iter_modules,
        run_passes,
    )
    from cassmantle_tpu.analysis.faultpoints import (
        FaultPointPass,
        load_registry,
    )

    registry = load_registry()
    assert set(registry) == set(chaos.FAULT_POINTS)
    fp = FaultPointPass()
    findings = run_passes(iter_modules(PACKAGE), [fp])
    assert findings == [], "\n".join(str(f) for f in findings)
    assert fp._seen == set(chaos.FAULT_POINTS)


def test_lint_all_includes_faultpoint_pass():
    import tools.lint_all as lint_all
    from cassmantle_tpu.analysis.faultpoints import FaultPointPass

    passes = lint_all.all_passes()
    assert any(isinstance(p, FaultPointPass) for p in passes)
