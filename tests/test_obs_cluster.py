"""Cluster observability tests (ISSUE 9, fast tier).

Covers the three tentpole legs and their acceptance bars:

- **cross-worker trace propagation**: traceparent format/parse, remote-
  parent span continuation, the peer/kill-switch gate, and the
  two-in-process-worker e2e — a room request redirected across workers
  yields ONE trace id whose merged ``/debugz?trace=&scope=cluster``
  view contains both workers' spans (http hop → queue-wait → device
  stage);
- **metrics federation**: the shard-merge exactness property (merge of
  per-worker snapshots == single-registry ground truth, histogram
  buckets included), the bounds-mismatch fallback, and the e2e
  ``/metrics?scope=cluster`` totals == sum of per-worker registries,
  with stale/dead peers marked;
- **SLO burn-rate engine**: state-machine units with an injectable
  clock (trip on the fast window, recover on the slow), and the e2e —
  an injected latency burst flips an ``/sloz`` objective to burning,
  ``slo.burn`` lands in the flight recorder, then recovers.

Plus the satellites: process self-metrics, per-room metric labels
(asserted through the two-worker fabric), and the bench counter-delta
helper.
"""

import asyncio
import dataclasses
import json
import math
import random
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.engine.content import (
    FakeContentBackend,
    hash_embed,
    hash_similarity,
)
from cassmantle_tpu.engine.game import Game
from cassmantle_tpu.engine.store import MemoryStore
from cassmantle_tpu.fabric.rooms import RoomFabric
from cassmantle_tpu.obs.recorder import FlightRecorder, flight_recorder
from cassmantle_tpu.obs.slo import Objective, SloEngine, _latency_good
from cassmantle_tpu.obs.trace import (
    format_traceparent,
    parse_traceparent,
    tracer,
)
from cassmantle_tpu.utils.logging import Metrics, merge_states, metrics


def make_cfg(num_rooms=1, **obs_kw):
    cfg = _tiny_config()
    return cfg.replace(
        game=dataclasses.replace(
            cfg.game, rate_limit_default=1e6, rate_limit_api=1e6,
            time_per_prompt=30.0),
        fabric=dataclasses.replace(
            cfg.fabric, num_rooms=num_rooms, heartbeat_s=30.0),
        obs=dataclasses.replace(
            cfg.obs, slo_eval_interval_s=300.0,
            process_sample_interval_s=60.0,
            cluster_fanout_timeout_s=1.0, **obs_kw),
    )


# -- traceparent wire format -----------------------------------------------

def test_traceparent_roundtrip_and_rejects():
    ctx = tracer.new_root_ctx()
    parsed = parse_traceparent(format_traceparent(ctx))
    assert (parsed.trace_id, parsed.span_id, parsed.sampled) == \
        (ctx.trace_id, ctx.span_id, ctx.sampled)
    unsampled = tracer.detached_ctx()
    assert format_traceparent(unsampled).endswith("-00")
    assert parse_traceparent(format_traceparent(unsampled)).sampled \
        is False
    # malformed input is dropped, never a fresh context
    for bad in (None, "", "garbage", "00-short-span-01",
                "01-" + "a" * 32 + "-" + "b" * 16 + "-01",
                "00-" + "g" * 32 + "-" + "b" * 16 + "-01"):
        assert parse_traceparent(bad) is None
    # marks are fresh per hop: request-local state never crosses
    ctx.marks["queue_wait_s"] = 1.0
    assert parse_traceparent(format_traceparent(ctx)).marks == {}


def test_span_continues_remote_parent():
    from cassmantle_tpu.obs.trace import Tracer

    tr = Tracer(capacity=8)
    remote = tr.new_root_ctx()
    with tr.span("b.hop", parent=remote) as h:
        assert h.trace_id == remote.trace_id
        with tr.span("b.child") as c:
            assert c.trace_id == remote.trace_id
    spans = {s["name"]: s for s in tr.get_trace(remote.trace_id)}
    assert spans["b.hop"]["parent_id"] == remote.span_id
    assert spans["b.child"]["parent_id"] == spans["b.hop"]["span_id"]
    # an unsampled remote context propagates ids but records nothing
    dark = parse_traceparent(
        format_traceparent(tr.detached_ctx()))
    with tr.span("b.dark", parent=dark) as h:
        assert h.trace_id == dark.trace_id
    assert tr.get_trace(dark.trace_id) is None


# -- registry read helpers + federation merge ------------------------------

def _metric_of(line: str) -> str:
    """The bare metric name of a Prometheus exposition line (strips
    labels and the value)."""
    return line.split(" ")[0].split("{")[0]

def test_registry_read_helpers_aggregate_labels():
    m = Metrics()
    m.inc("h.n", 2, labels={"room": "a"})
    m.inc("h.n", 3)
    assert m.counter_total("h.n") == 5
    assert m.counter_total("absent.name") == 0
    m.gauge("h.v", 1.0, labels={"w": "1"})
    m.gauge("h.v", 7.0)
    assert max(m.gauge_values("h.v")) == 7.0
    assert m.gauge_values("absent.name") == []
    m.observe("h.l_s", 0.05, labels={"room": "a"}, buckets=(0.1, 1.0))
    m.observe("h.l_s", 0.5, buckets=(0.1, 1.0))
    bounds, counts, total = m.hist_totals("h.l_s")
    assert bounds == (0.1, 1.0)
    assert counts == (1, 1, 0)
    assert total == 2
    assert m.hist_totals("absent.name") is None


def test_merge_states_matches_single_registry_ground_truth():
    """The exactness property: per-worker shard registries merged ==
    one registry that saw every event — counters AND histogram buckets
    (bucket counts are integers; merge must be exact, not a percentile
    re-estimate). States round-trip through JSON like the real wire."""
    rng = random.Random(7)
    bounds = (0.01, 0.1, 1.0)
    ground = Metrics(default_buckets=bounds)
    shards = [Metrics(default_buckets=bounds) for _ in range(3)]
    for _ in range(400):
        shard = rng.choice(shards)
        if rng.random() < 0.5:
            name = rng.choice(["a.hits", "b.misses"])
            labels = ({"room": rng.choice(["r1", "r2"])}
                      if rng.random() < 0.5 else None)
            v = rng.randint(1, 5)
            shard.inc(name, v, labels=labels)
            ground.inc(name, v, labels=labels)
        else:
            name = rng.choice(["a.lat_s", "b.wait_s"])
            v = rng.random() * 2.0
            shard.observe(name, v)
            ground.observe(name, v)
    states = [(f"w{i}", json.loads(json.dumps(s.dump_state())))
              for i, s in enumerate(shards)]
    merged = merge_states(states)
    assert merged.snapshot()["counters"] == \
        ground.snapshot()["counters"]

    def hist_lines(m):
        return [line for line in m.prometheus().splitlines()
                if "_bucket{" in line
                or _metric_of(line).endswith("_count")]

    assert hist_lines(merged) == hist_lines(ground)
    # _sum is a float reduction whose addition ORDER differs between
    # the shard path and the ground path — equal to fp tolerance
    for name in ("a.lat_s", "b.wait_s"):
        hm = merged.hist_totals(name)
        hg = ground.hist_totals(name)
        assert hm[1] == hg[1] and hm[2] == hg[2]
    mt = merged.snapshot()["timings"]
    gt = ground.snapshot()["timings"]
    for name in mt:
        assert math.isclose(mt[name]["mean_s"], gt[name]["mean_s"],
                            rel_tol=1e-9)


def test_merge_states_gauges_labeled_and_bounds_mismatch_falls_back():
    a = Metrics()
    a.gauge("x.depth", 3.0)
    a.observe("x.lat_s", 0.5, buckets=(0.1, 1.0))
    b = Metrics()
    b.gauge("x.depth", 5.0)
    b.observe("x.lat_s", 0.5, buckets=(0.2, 2.0))   # skewed ladder
    merged = merge_states([("wa", a.dump_state()),
                           ("wb", b.dump_state())])
    snap = merged.snapshot()
    # gauges: per-worker spread, never a meaningless sum
    assert snap["gauges"]['x.depth{worker="wa"}'] == 3.0
    assert snap["gauges"]['x.depth{worker="wb"}'] == 5.0
    # mismatched bounds: wb's series survives worker-labeled instead of
    # being mis-binned into wa's ladder
    assert snap["timings"]["x.lat_s"]["count"] == 1
    assert snap["timings"]['x.lat_s{worker="wb"}']["count"] == 1


# -- SLO engine units (injectable clock) -----------------------------------

class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def now(self):
        return self.t


def test_latency_good_bucket_math():
    bounds = (0.1, 1.0, 10.0)
    counts = (4, 3, 2, 1)          # last = +Inf overflow
    assert _latency_good(bounds, counts, 0.1) == 4
    assert _latency_good(bounds, counts, 0.5) == 7   # next bound up
    assert _latency_good(bounds, counts, 10.0) == 9
    assert _latency_good(bounds, counts, 99.0) == 9  # all but overflow


def test_slo_latency_trips_on_fast_window_recovers_on_slow():
    reg = Metrics()
    rec = FlightRecorder(capacity=64)
    clock = FakeClock()
    obj = Objective(name="lat", kind="latency", metric="svc.req_s",
                    threshold_s=0.1, objective_ratio=0.9)
    eng = SloEngine([obj], registry=reg, recorder=rec,
                    fast_window_s=60.0, slow_window_s=600.0,
                    clock=clock.now, min_eval_gap_s=0.0)
    buckets = (0.1, 1.0)
    # healthy traffic: no burn
    for _ in range(100):
        reg.observe("svc.req_s", 0.01, buckets=buckets)
    clock.t = 10.0
    out = eng.evaluate()
    assert out["lat"]["state"] == "ok"
    assert out["lat"]["fast_burn"] == 0.0
    # a latency burst blows the fast window -> burning + slo.burn event
    for _ in range(50):
        reg.observe("svc.req_s", 5.0, buckets=buckets)
    clock.t = 20.0
    out = eng.evaluate()
    assert out["lat"]["state"] == "burning"
    assert out["lat"]["fast_burn"] > 1.0
    assert [e["kind"] for e in rec.tail(kind="slo.")] == ["slo.burn"]
    assert reg.gauge_values("slo.burning") == [1.0]
    # fast window drains but the slow window still holds the burst:
    # STILL burning (recovery is slow-window gated)
    for _ in range(20):
        reg.observe("svc.req_s", 0.01, buckets=buckets)
    clock.t = 90.0
    out = eng.evaluate()
    assert out["lat"]["fast_burn"] <= 1.0
    assert out["lat"]["slow_burn"] > 1.0
    assert out["lat"]["state"] == "burning"
    # past the slow window: only healthy deltas remain -> recovered
    for _ in range(100):
        reg.observe("svc.req_s", 0.01, buckets=buckets)
    clock.t = 700.0
    out = eng.evaluate()
    assert out["lat"]["state"] == "ok"
    assert [e["kind"] for e in rec.tail(kind="slo.")] == \
        ["slo.burn", "slo.recovered"]


def test_slo_ratio_objective_sums_labeled_counters():
    reg = Metrics()
    rec = FlightRecorder(capacity=16)
    clock = FakeClock()
    obj = Objective(name="gen", kind="ratio", good=("x.ok",),
                    bad=("x.err",), objective_ratio=0.5)
    eng = SloEngine([obj], registry=reg, recorder=rec,
                    fast_window_s=60.0, slow_window_s=120.0,
                    clock=clock.now, min_eval_gap_s=0.0)
    # per-room labels must aggregate to worker truth
    reg.inc("x.ok", 8, labels={"room": "a"})
    reg.inc("x.ok", 2, labels={"room": "b"})
    clock.t = 10.0
    assert eng.evaluate()["gen"]["state"] == "ok"
    reg.inc("x.err", 30)
    clock.t = 20.0
    out = eng.evaluate()
    assert out["gen"]["state"] == "burning"
    assert out["gen"]["fast_burn"] > 1.0


def test_slo_gauge_objective_and_no_traffic():
    reg = Metrics()
    rec = FlightRecorder(capacity=16)
    clock = FakeClock()
    objs = [Objective(name="lag", kind="gauge", metric="x.lag",
                      bound=10.0),
            Objective(name="quiet", kind="ratio", good=("q.ok",),
                      bad=("q.err",), objective_ratio=0.99)]
    eng = SloEngine(objs, registry=reg, recorder=rec,
                    fast_window_s=60.0, slow_window_s=120.0,
                    clock=clock.now, min_eval_gap_s=0.0)
    clock.t = 1.0
    out = eng.evaluate()
    # absent gauge / zero traffic = no burn, never a false trip
    assert out["lag"]["state"] == "ok"
    assert out["quiet"]["fast_burn"] == 0.0
    reg.gauge("x.lag", 20.0, labels={"store": "a"})
    reg.gauge("x.lag", 3.0, labels={"store": "b"})   # max() wins
    clock.t = 2.0
    out = eng.evaluate()
    assert out["lag"]["state"] == "burning"
    assert out["lag"]["fast_burn"] == 2.0
    reg.gauge("x.lag", 3.0, labels={"store": "a"})
    clock.t = 3.0
    assert eng.evaluate()["lag"]["state"] == "ok"


def test_slo_eval_gap_rate_limits_scrapes():
    reg = Metrics()
    clock = FakeClock()
    eng = SloEngine([Objective(name="g", kind="gauge", metric="x.g",
                               bound=1.0)],
                    registry=reg, recorder=FlightRecorder(capacity=4),
                    fast_window_s=60.0, slow_window_s=120.0,
                    clock=clock.now, min_eval_gap_s=5.0)
    clock.t = 1.0
    eng.evaluate()
    first = reg.counter_total("slo.evals")
    clock.t = 2.0
    eng.evaluate()                       # inside the gap: cached
    assert reg.counter_total("slo.evals") == first
    clock.t = 7.0
    eng.evaluate()
    assert reg.counter_total("slo.evals") == first + 1


# -- process self-metrics --------------------------------------------------

def test_process_metrics_sample():
    from cassmantle_tpu.obs.process import ProcessMetrics

    reg = Metrics()
    clock = FakeClock(100.0)
    proc = ProcessMetrics(registry=reg, clock=clock.now)
    clock.t = 105.0
    proc.sample()
    gauges = reg.snapshot()["gauges"]
    assert gauges["process.uptime_s"] == 5.0
    assert gauges["process.rss_bytes"] > 1e6      # a real python process
    assert gauges["process.cpu_s"] > 0.0


# -- bench counter deltas --------------------------------------------------

def test_bench_counter_deltas_select_diagnosis_counters():
    import bench

    before = {"jit.recompiles": 1.0, "scorer.embed_cache_hits": 5.0,
              "http.init": 3.0}
    after = {"jit.recompiles": 4.0, "scorer.embed_cache_hits": 5.0,
             "http.init": 9.0, "score.dispatch_hangs": 2.0,
             'stage.denoise.preemptions': 1.0,
             'game.guesses{room="r"}': 7.0}
    deltas = bench._counter_deltas(before, after)
    # unchanged and non-diagnosis counters (http.init, game.guesses)
    # stay out; new diagnosis counters count from zero
    assert deltas == {"jit.recompiles": 3, "score.dispatch_hangs": 2,
                      "stage.denoise.preemptions": 1}


# -- e2e: SLO through /sloz, /readyz, /debugz ------------------------------

@pytest.mark.asyncio
async def test_latency_burst_flips_sloz_and_recorder_then_recovers():
    """Acceptance: an injected latency burst flips the /sloz
    score_latency objective to burning, slo.burn lands in the flight
    recorder (readable at /debugz), /readyz carries the advisory block
    without gating on it — then the burst drains and it recovers."""
    from cassmantle_tpu.server.app import create_app

    cfg = make_cfg(slo_fast_window_s=0.3, slo_slow_window_s=0.8,
                   slo_score_p99_s=0.05)
    game = Game(cfg, MemoryStore(), FakeContentBackend(image_size=32),
                hash_embed, hash_similarity)
    app = create_app(game, cfg, start_timer=False)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        watermark = flight_recorder.stats()["total_recorded"]
        res = await client.get("/sloz")
        body = await res.json()
        assert body["objectives"]["score_latency"]["state"] == "ok"
        assert body["windows"]["fast_s"] == 0.3
        # the injected burst: 40 requests at 1s against a 50ms target
        for _ in range(40):
            metrics.observe("http.compute_score_s", 1.0)
        # step past the engine's scrape-rate-limit gap (fast_window/10)
        await asyncio.sleep(0.05)
        res = await client.get("/sloz")
        body = await res.json()
        assert body["objectives"]["score_latency"]["state"] == "burning"
        assert "score_latency" in body["burning"]
        # /readyz carries the block but stays 200 (advisory, not gating)
        res = await client.get("/readyz")
        assert res.status == 200
        assert (await res.json())["slo"]["burning"] == ["score_latency"]
        # the burn event is in the flight recorder, visible at /debugz
        dbg = await client.get("/debugz?kind=slo.")
        events = [e for e in (await dbg.json())["events"]
                  if e["seq"] > watermark]
        assert [e["kind"] for e in events] == ["slo.burn"]
        assert events[0]["objective"] == "score_latency"
        # drain past the slow window with healthy traffic -> recovered.
        # /readyz is read FIRST: its advisory block must evaluate on
        # read (rate-limited), so it stays live even when the
        # background loop is off (CASSMANTLE_NO_SLO) — a frozen
        # first-ever verdict would still say burning here
        await asyncio.sleep(0.9)
        for _ in range(100):
            metrics.observe("http.compute_score_s", 0.001)
        res = await client.get("/readyz")
        assert (await res.json())["slo"]["burning"] == []
        res = await client.get("/sloz")
        assert (await res.json())["objectives"]["score_latency"][
            "state"] == "ok"
        dbg = await client.get("/debugz?kind=slo.")
        kinds = [e["kind"] for e in (await dbg.json())["events"]
                 if e["seq"] > watermark]
        assert kinds == ["slo.burn", "slo.recovered"]
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_inbound_traceparent_gate(monkeypatch):
    """A loopback-presented traceparent continues the trace; with the
    cluster-obs kill switch set it is ignored (fresh trace); malformed
    input never joins anything."""
    from cassmantle_tpu.server.app import create_app

    cfg = make_cfg()
    game = Game(cfg, MemoryStore(), FakeContentBackend(image_size=32),
                hash_embed, hash_similarity)
    app = create_app(game, cfg, start_timer=False)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        ctx = tracer.new_root_ctx()
        tp = format_traceparent(ctx)
        res = await client.get("/client/status",
                               params={"traceparent": tp})
        assert res.headers["X-Trace-Id"] == ctx.trace_id
        # header form too (the peer fan-out channel)
        ctx2 = tracer.new_root_ctx()
        res = await client.get(
            "/client/status",
            headers={"traceparent": format_traceparent(ctx2)})
        assert res.headers["X-Trace-Id"] == ctx2.trace_id
        # malformed: dropped, a fresh trace is minted
        res = await client.get("/client/status",
                               params={"traceparent": "garbage"})
        assert res.headers["X-Trace-Id"] not in (ctx.trace_id,
                                                 ctx2.trace_id)
        # kill switch: the same valid context is ignored
        monkeypatch.setenv("CASSMANTLE_NO_CLUSTER_OBS", "1")
        res = await client.get("/client/status",
                               params={"traceparent": tp})
        assert res.headers["X-Trace-Id"] != ctx.trace_id
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_cluster_secret_legs_for_external_bearers(monkeypatch):
    """The redirect channel is carried back by the UNTRUSTED player:
    with every source-based leg off (loopback patched away), a
    traceparent is honored only with a valid ``tracesig`` under the
    store-distributed cluster secret, /debugz and cluster /metrics
    admit only the ``X-Cluster-Auth`` token, and forgeries fail."""
    from cassmantle_tpu.server import app as app_mod
    from cassmantle_tpu.server.app import create_app

    cfg = make_cfg()
    game = Game(cfg, MemoryStore(), FakeContentBackend(image_size=32),
                hash_embed, hash_similarity)
    app = create_app(game, cfg, start_timer=False)
    fabric = app[app_mod._FABRIC]
    await fabric._ensure_cluster_key()
    assert fabric._cluster_key
    # a second fabric over the same store derives the SAME secret (the
    # boot race converges on whichever write won)
    other = RoomFabric(cfg, game.store, lambda r, s: game,
                       worker_id="w2", heartbeat=False)
    await other._ensure_cluster_key()
    assert other.cluster_token() == fabric.cluster_token()

    client = TestClient(TestServer(app))
    await client.start_server()
    # simulate an external (non-loopback, non-member) bearer
    monkeypatch.setattr(app_mod, "_is_loopback", lambda request: False)
    try:
        ctx = tracer.new_root_ctx()
        tp = format_traceparent(ctx)
        # bare context from an outsider: rejected
        res = await client.get("/client/status",
                               params={"traceparent": tp})
        assert res.headers["X-Trace-Id"] != ctx.trace_id
        # forged signature: rejected
        res = await client.get(
            "/client/status",
            params={"traceparent": tp, "tracesig": "0" * 32})
        assert res.headers["X-Trace-Id"] != ctx.trace_id
        # the signature a redirecting worker mints: honored
        res = await client.get(
            "/client/status",
            params={"traceparent": tp,
                    "tracesig": fabric.sign_trace(tp)})
        assert res.headers["X-Trace-Id"] == ctx.trace_id
        # an OTel-style client auto-injecting its OWN traceparent
        # header must not shadow the signed query context the redirect
        # pinned — the channels are judged independently
        minted = format_traceparent(tracer.new_root_ctx())
        res = await client.get(
            "/client/status",
            params={"traceparent": tp,
                    "tracesig": fabric.sign_trace(tp)},
            headers={"traceparent": minted})
        assert res.headers["X-Trace-Id"] == ctx.trace_id
        # operator/cluster surfaces: refused without the token,
        # admitted with it
        for path in ("/debugz", "/metrics?format=state",
                     "/metrics?scope=cluster"):
            res = await client.get(path)
            assert res.status == 403, path
            res = await client.get(
                path, headers={"X-Cluster-Auth": "not-the-token"})
            assert res.status == 403, path
            res = await client.get(
                path,
                headers={"X-Cluster-Auth": fabric.cluster_token()})
            assert res.status == 200, path
    finally:
        await client.close()


# -- e2e acceptance: two in-process fabric workers -------------------------

async def _start_worker(cfg, store, worker_id, service):
    """One fabric worker on a real socket: its own supervisor and
    membership identity, sharing the store (the cluster's coordination
    plane) and the serving stack (this is one process)."""
    from cassmantle_tpu.server.app import create_app
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    sup = ServingSupervisor()

    def factory(room, room_store):
        return Game(cfg, room_store, service.content_backend,
                    embed=service.embed, similarity=service.similarity,
                    supervisor=sup, room=room)

    fabric = RoomFabric(cfg, store, factory, worker_id=worker_id,
                        start_timers=False, heartbeat=True,
                        supervisor=sup)
    server = TestServer(create_app(fabric, cfg, start_timer=False))
    await server.start_server()
    url = f"http://127.0.0.1:{server.port}"
    fabric.membership.addr = url
    return server, fabric, url


async def _sync_membership(fabrics):
    for f in fabrics:
        await f.membership.heartbeat(len(f._games))
    for f in fabrics:
        live = await f.membership.refresh()
        await f._handle_moves(f._apply_membership(live))


@pytest.mark.asyncio
async def test_two_workers_one_trace_and_exact_federation():
    """The ISSUE 9 acceptance path, in-process: a room request
    redirected across workers yields ONE trace id whose merged
    /debugz?trace=&scope=cluster view spans both workers (http hop →
    queue-wait → device stage); /metrics?scope=cluster counter totals
    equal the sum of the per-worker registry states exactly, histogram
    buckets included; per-room labels and stale/dead peer marking ride
    along. (Both workers share this process's global registry/tracer —
    the federation math is what's under test, and 'sum of per-worker
    registries' holds exactly either way.)"""
    import aiohttp

    from cassmantle_tpu.serving.service import InferenceService

    cfg = make_cfg(num_rooms=8)
    store = MemoryStore()
    service = InferenceService(
        cfg, backend=FakeContentBackend(image_size=32))
    server_a, fabric_a, url_a = await _start_worker(
        cfg, store, "w-a", service)
    server_b, fabric_b, url_b = await _start_worker(
        cfg, store, "w-b", service)
    http = aiohttp.ClientSession()
    try:
        await _sync_membership([fabric_a, fabric_b])
        placement = fabric_a.directory.placement()
        b_rooms = [r for r, w in placement.items() if w == "w-b"]
        assert b_rooms, "8 rooms over 2 workers: w-b must own some"
        room = b_rooms[0]
        q = f"?room={room}&session=s-hop"

        # the 307 pins room+session+traceparent+tracesig on the
        # Location (the signature is what lets an external bearer's
        # follow-up keep the trace)
        res = await http.get(url_a + "/fetch/contents" + q,
                             allow_redirects=False)
        assert res.status == 307
        loc = res.headers["Location"]
        assert loc.startswith(url_b) and "traceparent=00-" in loc
        assert "tracesig=" in loc

        # follow the hop for real: contents, then a scored guess
        res = await http.get(url_a + "/fetch/contents" + q)
        assert res.status == 200 and str(res.url).startswith(url_b)
        mask = (await res.json())["prompt"]["masks"][0]
        res = await http.post(url_a + "/compute_score" + q,
                              json={"inputs": {str(mask): "storm"}})
        assert res.status == 200 and str(res.url).startswith(url_b)
        trace_id = res.headers["X-Trace-Id"]

        # ONE trace id across the hop: the merged cluster view holds
        # both workers' http spans (parent-linked) down to the
        # device-synchronized scorer stage
        dbg = await http.get(
            url_a + f"/debugz?trace={trace_id}&scope=cluster")
        assert dbg.status == 200
        data = await dbg.json()
        assert data["scope"] == "cluster"
        assert data["peers"]["w-a"]["status"] == "self"
        assert data["peers"]["w-b"]["status"] == "ok"
        spans = data["spans"]
        assert all(s["trace_id"] == trace_id for s in spans)
        hops = {s["attrs"]["worker"]: s for s in spans
                if s["name"] == "http.post /compute_score"}
        assert set(hops) == {"w-a", "w-b"}
        assert hops["w-a"]["attrs"]["status"] == 307
        assert hops["w-b"]["attrs"]["status"] == 200
        assert hops["w-b"]["parent_id"] == hops["w-a"]["span_id"]
        names = {s["name"] for s in spans}
        assert {"game.score", "score.queue_wait",
                "score.batch_service"} <= names
        stage = [s for s in spans if s["name"] == "scorer.encode_s"]
        assert stage and stage[0]["attrs"]["device_synced"] is True

        # per-room labels: the scored guess and the room's generation
        # carry room= labels in the registry
        snap = await (await http.get(url_a + "/metrics")).json()
        assert snap["counters"][f'game.guesses{{room="{room}"}}'] >= 1
        assert f'round.generate_s{{room="{room}"}}' in snap["timings"]

        # federation exactness: cluster totals == sum of the per-worker
        # registry states, histogram buckets included
        sa = await (await http.get(
            url_a + "/metrics?format=state")).json()
        sb = await (await http.get(
            url_b + "/metrics?format=state")).json()
        assert sa["worker"] == "w-a" and sb["worker"] == "w-b"
        res = await http.get(url_a + "/metrics?scope=cluster",
                             headers={"Accept": "text/plain"})
        got = await res.text()
        expected = merge_states([("w-a", sa["state"]),
                                 ("w-b", sb["state"])]).prometheus()

        def exact_lines(text):
            return sorted(
                line for line in text.splitlines()
                if not line.startswith("#")
                and (_metric_of(line).endswith(
                        ("_total", "_count", "_sum"))
                     or "_bucket{" in line))

        assert exact_lines(got) == exact_lines(expected)
        assert 'cassmantle_federation_peer_up{worker="w-b"} 1' in got

        # stale and dark peers are MARKED, never silently dropped
        await store.hset(
            "fabric:workers", "w-stale",
            json.dumps({"addr": "http://127.0.0.1:1", "rooms": 0,
                        "t": time.time() - 9999}))
        await store.hset(
            "fabric:workers", "w-dark",
            json.dumps({"addr": "http://127.0.0.1:9", "rooms": 0,
                        "t": time.time()}))
        snap = await (await http.get(
            url_a + "/metrics?scope=cluster")).json()
        fed = snap["federation"]
        assert fed["w-a"]["status"] == "self"
        assert fed["w-b"]["status"] == "ok"
        assert fed["w-stale"]["status"] == "stale"
        assert fed["w-dark"]["status"] == "error"
        assert snap["gauges"]['federation.peer_up{worker="w-dark"}'] \
            == 0.0
        assert snap["gauges"]['federation.peer_up{worker="w-b"}'] == 1.0
    finally:
        await http.close()
        await server_a.close()
        await server_b.close()
