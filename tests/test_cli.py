"""CLI surface (`python -m cassmantle_tpu`): dispatch + train smoke runs.

The reference has no CLI (launch is `uvicorn main:app`, reference
requirements.txt:2); this framework fronts every runnable surface through
one entry point, so the dispatch table and both training loops get tests.
Training smoke runs use the tiny test config on the virtual CPU devices.
"""

import numpy as np
import pytest

from cassmantle_tpu.__main__ import main


def test_usage_and_unknown_command(capsys):
    assert main([]) == 2
    assert main(["no-such-command"]) == 2
    assert main(["--help"]) == 0
    out = capsys.readouterr()
    assert "train-diffusion" in out.err


def test_version(capsys):
    assert main(["version"]) == 0
    from cassmantle_tpu import __version__

    assert __version__ in capsys.readouterr().out


def test_train_diffusion_smoke(tmp_path, capsys):
    rc = main([
        "train-diffusion", "--config", "test", "--steps", "3",
        "--batch", "8", "--image-size", "64", "--dp", "-1",
        "--log-every", "1",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--checkpoint-every", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[diffusion] step 2 loss" in out
    # resume path: a second run starts from the saved final step
    rc = main([
        "train-diffusion", "--config", "test", "--steps", "3",
        "--batch", "8", "--image-size", "64",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    assert rc == 0
    assert "resumed from step 3" in capsys.readouterr().out


def test_train_lm_smoke(capsys):
    rc = main([
        "train-lm", "--config", "test", "--steps", "2", "--batch", "8",
        "--seq-len", "32", "--log-every", "1",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[lm] step 1 loss" in out


def test_train_lm_token_file(tmp_path, capsys):
    stream = np.arange(8 * 32 * 2, dtype=np.int32) % 50
    path = tmp_path / "tokens.npy"
    np.save(path, stream)
    rc = main([
        "train-lm", "--config", "test", "--steps", "1", "--batch", "8",
        "--seq-len", "32", "--tokens", str(path), "--log-every", "1",
    ])
    assert rc == 0
    assert "[lm] step 0 loss" in capsys.readouterr().out
