"""CLI surface (`python -m cassmantle_tpu`): dispatch + train smoke runs.

The reference has no CLI (launch is `uvicorn main:app`, reference
requirements.txt:2); this framework fronts every runnable surface through
one entry point, so the dispatch table and both training loops get tests.
Training smoke runs use the tiny test config on the virtual CPU devices.
"""

import numpy as np
import pytest

from cassmantle_tpu.__main__ import main


def test_usage_and_unknown_command(capsys):
    assert main([]) == 2
    assert main(["no-such-command"]) == 2
    assert main(["--help"]) == 0
    out = capsys.readouterr()
    assert "train-diffusion" in out.err


def test_version(capsys):
    assert main(["version"]) == 0
    from cassmantle_tpu import __version__

    assert __version__ in capsys.readouterr().out


def test_train_diffusion_smoke(tmp_path, capsys):
    rc = main([
        "train-diffusion", "--config", "test", "--steps", "3",
        "--batch", "8", "--image-size", "64", "--dp", "-1",
        "--log-every", "1",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--checkpoint-every", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[diffusion] step 2 loss" in out
    # resume path: a second run starts from the saved final step
    rc = main([
        "train-diffusion", "--config", "test", "--steps", "3",
        "--batch", "8", "--image-size", "64",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
    ])
    assert rc == 0
    assert "resumed from step 3" in capsys.readouterr().out


def test_train_lm_smoke(capsys):
    rc = main([
        "train-lm", "--config", "test", "--steps", "2", "--batch", "8",
        "--seq-len", "32", "--log-every", "1",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[lm] step 1 loss" in out


def test_train_lm_token_file(tmp_path, capsys):
    stream = np.arange(8 * 32 * 2, dtype=np.int32) % 50
    path = tmp_path / "tokens.npy"
    np.save(path, stream)
    rc = main([
        "train-lm", "--config", "test", "--steps", "1", "--batch", "8",
        "--seq-len", "32", "--tokens", str(path), "--log-every", "1",
    ])
    assert rc == 0
    assert "[lm] step 0 loss" in capsys.readouterr().out


def test_quality_gate_thresholds():
    """config.QualityGateConfig enforcement (VERDICT r4 #3): the gate
    annotates per-preset verdicts, fails presets under threshold and a
    degraded anchor, and passes a clean report."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "clip_report_mod",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "clip_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    def report(anchor_sim, parity):
        return {"presets": {
            "ddim50": {"clip_sim_mean": anchor_sim},
            "turbo": {"clip_sim_mean": anchor_sim * parity,
                      "parity_vs_ddim50": parity},
        }}

    clean = report(0.30, 0.99)
    assert mod.apply_quality_gate(clean) == []
    assert clean["presets"]["turbo"]["gate"]["passed"]
    assert clean["presets"]["ddim50"]["gate"]["passed"]

    low_parity = report(0.30, 0.90)  # turbo gates at 0.95
    fails = mod.apply_quality_gate(low_parity)
    assert len(fails) == 1 and "turbo" in fails[0]
    assert not low_parity["presets"]["turbo"]["gate"]["passed"]

    dead_anchor = report(0.05, 0.99)  # uniform degradation
    fails = mod.apply_quality_gate(dead_anchor)
    assert any("anchor" in f for f in fails)

    # a preset with no configured threshold is reported, never gated
    ungated = {"presets": {"ddim50": {"clip_sim_mean": 0.3},
                           "exotic": {"clip_sim_mean": 0.1,
                                      "parity_vs_ddim50": 0.33}}}
    assert mod.apply_quality_gate(ungated) == []


def test_weights_drill_requires_real_weights_for_round(tmp_path):
    """The drill's LM-decoded-round leg must refuse to 'pass' on random
    init at full config — a provisioned-host check, not a plumbing one
    (exit 5). --tiny remains the plumbing path (covered by the watcher
    smoke)."""
    from cassmantle_tpu.__main__ import main

    rc = main(["weights-drill", "--platform", "cpu",
               "--weights", str(tmp_path / "nope"),
               "--skip-fetch", "--skip-quantize", "--skip-clip",
               "--skip-lm-ab"])
    assert rc == 5
