import asyncio

import numpy as np
import pytest

from cassmantle_tpu.serving.queue import BatchingQueue, QueueFull


@pytest.mark.asyncio
async def test_coalesces_concurrent_submissions():
    batches = []

    def handler(items):
        batches.append(len(items))
        return [x * 2 for x in items]

    q = BatchingQueue(handler, max_batch=64, max_delay_ms=30)
    results = await asyncio.gather(*(q.submit(i) for i in range(10)))
    assert results == [i * 2 for i in range(10)]
    assert sum(batches) == 10
    assert len(batches) <= 3  # coalesced, not 10 singleton batches
    await q.stop()


@pytest.mark.asyncio
async def test_respects_max_batch():
    batches = []

    def handler(items):
        batches.append(len(items))
        return items

    q = BatchingQueue(handler, max_batch=4, max_delay_ms=50)
    await asyncio.gather(*(q.submit(i) for i in range(10)))
    assert max(batches) <= 4
    await q.stop()


@pytest.mark.asyncio
async def test_handler_exception_propagates():
    def handler(items):
        raise ValueError("boom")

    q = BatchingQueue(handler, max_batch=4, max_delay_ms=5)
    with pytest.raises(ValueError):
        await q.submit(1)
    # queue stays alive for subsequent batches
    q.handler = lambda items: items
    assert await q.submit(7) == 7
    await q.stop()


@pytest.mark.asyncio
async def test_backpressure_queue_full():
    started = asyncio.Event()

    def slow_handler(items):
        return items

    q = BatchingQueue(slow_handler, max_batch=1, max_delay_ms=1,
                      max_pending=2)
    # saturate without draining: stop collector first
    q.start()
    await q.stop()
    q._task = object()  # prevent restart by submit
    q._queue.put_nowait((0, asyncio.get_event_loop().create_future()))
    q._queue.put_nowait((1, asyncio.get_event_loop().create_future()))
    with pytest.raises(QueueFull):
        await q.submit(2)


@pytest.mark.asyncio
async def test_latency_bounded_by_delay_window():
    def handler(items):
        return items

    q = BatchingQueue(handler, max_batch=1024, max_delay_ms=20)
    loop = asyncio.get_event_loop()
    t0 = loop.time()
    await q.submit("x")
    elapsed = loop.time() - t0
    assert elapsed < 1.0  # window + dispatch, far under a second
    await q.stop()


def test_concurrent_rounds_coalesce_prompt_decodes():
    """InferenceService.generate_content routes the LM decode through
    the prompt queue: 3 rounds generating concurrently become ONE
    batched generate_batch call (VERDICT r4 #4 — prompts no longer
    decode one per call), and each round's text matches what a single
    decode of its seed would have produced."""
    import asyncio

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.service import InferenceService

    svc = InferenceService(test_config())
    seen_batches = []
    orig = svc.backend.prompt_gen.generate_batch

    def spying(texts, max_new_tokens=None):
        seen_batches.append(list(texts))
        return orig(texts, max_new_tokens)

    svc.backend.prompt_gen.generate_batch = spying

    async def run():
        svc.prompt_queue.start()
        seeds = ["the storm rolled", "a quiet harbor", "the last train"]
        out = await asyncio.gather(
            *(svc.generate_content(s, False) for s in seeds))
        await svc.stop()
        return out

    contents = asyncio.run(run())
    svc.backend.prompt_gen.generate_batch = orig
    # one coalesced decode batch carried all three seeds (the queue may
    # split under scheduling jitter, but must not degrade to singletons)
    decode_batches = [b for b in seen_batches if len(b) > 1]
    assert decode_batches, f"no coalescing happened: {seen_batches}"
    assert sum(len(b) for b in seen_batches) == 3
    for content in contents:
        assert content.prompt_text and content.image is not None


def test_soak_run_smoke():
    """The sustained-serving soak harness (bench.py:soak_run) drives N
    rounds of content generation under continuous guess pressure and
    returns latency samples — smoke-tested here at tiny config on CPU;
    the suite's `soak` entry reports p50/p99 from the same code path."""
    import asyncio

    from bench import soak_run
    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.service import InferenceService

    svc = InferenceService(test_config())
    elapsed, lats, errors = asyncio.run(soak_run(svc, rounds=2, workers=4))
    assert elapsed > 0
    assert len(lats) >= 4   # pressure loops actually scored guesses
    assert errors == 0
