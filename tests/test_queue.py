import asyncio
import threading
import time

import numpy as np
import pytest

from cassmantle_tpu.serving.queue import (
    BatchingQueue,
    DeadlineExceeded,
    DispatchTimeout,
    QueueFull,
    QueueStopped,
)


@pytest.mark.asyncio
async def test_coalesces_concurrent_submissions():
    batches = []

    def handler(items):
        batches.append(len(items))
        return [x * 2 for x in items]

    q = BatchingQueue(handler, max_batch=64, max_delay_ms=30)
    results = await asyncio.gather(*(q.submit(i) for i in range(10)))
    assert results == [i * 2 for i in range(10)]
    assert sum(batches) == 10
    assert len(batches) <= 3  # coalesced, not 10 singleton batches
    await q.stop()


@pytest.mark.asyncio
async def test_respects_max_batch():
    batches = []

    def handler(items):
        batches.append(len(items))
        return items

    q = BatchingQueue(handler, max_batch=4, max_delay_ms=50)
    await asyncio.gather(*(q.submit(i) for i in range(10)))
    assert max(batches) <= 4
    await q.stop()


@pytest.mark.asyncio
async def test_handler_exception_propagates():
    def handler(items):
        raise ValueError("boom")

    q = BatchingQueue(handler, max_batch=4, max_delay_ms=5)
    with pytest.raises(ValueError):
        await q.submit(1)
    # queue stays alive for subsequent batches
    q.handler = lambda items: items
    assert await q.submit(7) == 7
    await q.stop()


@pytest.mark.asyncio
async def test_backpressure_queue_full():
    started = asyncio.Event()

    def slow_handler(items):
        return items

    q = BatchingQueue(slow_handler, max_batch=1, max_delay_ms=1,
                      max_pending=2)
    # saturate without draining: stop collector first
    q.start()
    await q.stop()
    q._task = object()  # prevent restart by submit
    q._queue.put_nowait((0, asyncio.get_event_loop().create_future()))
    q._queue.put_nowait((1, asyncio.get_event_loop().create_future()))
    with pytest.raises(QueueFull):
        await q.submit(2)


@pytest.mark.asyncio
async def test_latency_bounded_by_delay_window():
    def handler(items):
        return items

    q = BatchingQueue(handler, max_batch=1024, max_delay_ms=20)
    loop = asyncio.get_event_loop()
    t0 = loop.time()
    await q.submit("x")
    elapsed = loop.time() - t0
    assert elapsed < 1.0  # window + dispatch, far under a second
    await q.stop()


@pytest.mark.asyncio
async def test_stop_fails_pending_futures():
    """Shutdown with queued items must fail their futures, not leave the
    awaiting callers hanging forever (ISSUE 2 satellite)."""
    q = BatchingQueue(lambda items: items, max_batch=1, max_delay_ms=1,
                      max_pending=8, name="stoptest")
    # park items in the queue with no collector running
    loop = asyncio.get_running_loop()
    futs = [loop.create_future() for _ in range(3)]
    for i, fut in enumerate(futs):
        q._queue.put_nowait((i, fut))
    await q.stop()
    for fut in futs:
        assert fut.done()
        with pytest.raises(QueueStopped):
            fut.result()
    # QueueStopped degrades like backpressure at existing call sites
    assert issubclass(QueueStopped, QueueFull)


@pytest.mark.asyncio
async def test_stop_mid_collect_window_fails_popped_items():
    """stop() must also fail items the collector already popped off the
    queue (waiting out the coalescing window) — they are invisible to
    the queue drain and would otherwise dangle forever."""
    q = BatchingQueue(lambda items: items, max_batch=64,
                      max_delay_ms=10_000, name="midstop")
    fut = asyncio.ensure_future(q.submit("x"))
    await asyncio.sleep(0.05)       # collector popped "x", awaits window
    await q.stop()
    with pytest.raises(QueueStopped):
        await fut


@pytest.mark.asyncio
async def test_watchdog_ignores_queue_wait_behind_other_dispatch():
    """Time queued on the shared dispatch thread behind ANOTHER queue's
    legitimate slow handler must not count toward this queue's hang
    deadline — only a handler actually running can be declared wedged."""
    slow_started = threading.Event()

    def slow_but_legit(items):
        slow_started.set()
        time.sleep(0.6)
        return items

    qa = BatchingQueue(slow_but_legit, max_delay_ms=1, name="slowq")
    qb = BatchingQueue(lambda items: items, max_delay_ms=1,
                      hang_timeout_s=0.2, name="fastq")
    ta = asyncio.ensure_future(qa.submit("a"))
    await asyncio.to_thread(slow_started.wait, 2.0)   # slowq occupies it
    # qb's batch waits ~0.6s queued (> its 0.2s hang deadline) and must
    # still succeed rather than raise DispatchTimeout
    assert await qb.submit("b") == "b"
    assert await ta == "a"
    await qa.stop()
    await qb.stop()


@pytest.mark.asyncio
async def test_watchdog_hang_clock_arms_at_handler_start_not_submit():
    """A handler that STARTS late (behind another queue's slow-but-legit
    dispatch) gets its full hang budget from the moment it runs: the
    hang clock must arm at handler start, not at submit. Before the fix,
    the first watchdog window expiring after the late start declared the
    healthy handler wedged — failing the batch with DispatchTimeout and
    disowning a healthy in-flight dispatch — even though it had run for
    only a fraction of its budget."""
    slow_started = threading.Event()

    def slow_but_legit(items):
        slow_started.set()
        time.sleep(0.75)
        return items

    def healthy_but_late(items):
        # runs 0.45s — inside the 0.5s hang budget from ITS start, but
        # spanning the submit-relative window boundary at t=1.0
        time.sleep(0.45)
        return items

    qa = BatchingQueue(slow_but_legit, max_delay_ms=1, name="slowq2")
    qb = BatchingQueue(healthy_but_late, max_delay_ms=1,
                       hang_timeout_s=0.5, name="lateq")
    ta = asyncio.ensure_future(qa.submit("a"))
    await asyncio.to_thread(slow_started.wait, 2.0)
    assert await qb.submit("b") == "b"
    assert await ta == "a"
    await qa.stop()
    await qb.stop()


@pytest.mark.asyncio
async def test_submit_deadline_fails_future_under_hung_handler():
    """A wedged handler (hung XLA call) must not hang submitters: the
    per-request deadline fails the future on time (acceptance criterion:
    'fails pending submit futures at their deadline instead of hanging
    the test')."""
    release = threading.Event()

    def hung_handler(items):
        release.wait(timeout=10.0)
        return items

    q = BatchingQueue(hung_handler, max_batch=4, max_delay_ms=1,
                      hang_timeout_s=2.0, name="hungtest")
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        await q.submit("x", deadline_s=0.2)
    assert time.monotonic() - t0 < 1.5
    release.set()          # unwedge the dispatch thread for later tests
    await q.stop()


@pytest.mark.asyncio
async def test_watchdog_replaces_wedged_dispatch_thread():
    """The hang watchdog fails the wedged batch with DispatchTimeout,
    flips the supervisor degraded, and later batches dispatch on a FRESH
    thread — the wedge doesn't serialize the rest of serving behind it."""
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    release = threading.Event()
    calls = []

    def handler(items):
        calls.append(list(items))
        if items == ["wedge"]:
            release.wait(timeout=10.0)
        return items

    sup = ServingSupervisor(degraded_cooldown_s=30.0)
    q = BatchingQueue(handler, max_batch=1, max_delay_ms=1,
                      hang_timeout_s=0.3, supervisor=sup, name="wdtest")
    assert not sup.watchdog_degraded
    with pytest.raises(DispatchTimeout):
        await q.submit("wedge")
    assert sup.watchdog_degraded
    # the replacement thread serves the next batch while the old one is
    # still wedged
    assert await q.submit("after") == "after"
    release.set()
    await q.stop()


@pytest.mark.asyncio
async def test_degraded_supervisor_tightens_admission():
    """While degraded, the queue admits only degraded_max_pending items
    (shed early: deep backlogs behind a sick device are doomed work)."""
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    sup = ServingSupervisor(degraded_cooldown_s=60.0)
    q = BatchingQueue(lambda items: items, max_pending=64,
                      degraded_max_pending=2, supervisor=sup,
                      name="degradetest")
    q.start()
    await q.stop()
    q._task = object()      # park the collector so items pile up
    loop = asyncio.get_running_loop()
    q._queue.put_nowait((0, loop.create_future()))
    q._queue.put_nowait((1, loop.create_future()))
    # healthy: plenty of room under max_pending
    fut = asyncio.ensure_future(q.submit(2))
    await asyncio.sleep(0)
    assert not fut.done()
    sup.note_dispatch_overrun("degradetest")
    with pytest.raises(QueueFull):
        await q.submit(3)
    fut.cancel()
    q._task = None
    await q.stop()


@pytest.mark.slow
def test_concurrent_rounds_coalesce_prompt_decodes():
    """InferenceService.generate_content routes the LM decode through
    the prompt queue: 3 rounds generating concurrently become ONE
    batched generate_batch call (VERDICT r4 #4 — prompts no longer
    decode one per call), and each round's text matches what a single
    decode of its seed would have produced.

    slow (round 21): this test and the soak smoke below each build a
    full real-pipeline InferenceService (~50 s of compiles apiece on a
    1-core host) and had grown the default tier past its 870 s window —
    the same overflow the round-14 module demotions fixed. The queue's
    coalescing/backpressure/deadline semantics stay tier-1 via the
    mock-handler units above, and the service-integration path stays
    tier-1 via test_server's full-stack round; the full tier keeps the
    prompt-decode coalescing bar itself."""
    import asyncio

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.service import InferenceService

    svc = InferenceService(test_config())
    seen_batches = []
    orig = svc.backend.prompt_gen.generate_batch

    def spying(texts, max_new_tokens=None):
        seen_batches.append(list(texts))
        return orig(texts, max_new_tokens)

    svc.backend.prompt_gen.generate_batch = spying

    async def run():
        svc.prompt_queue.start()
        seeds = ["the storm rolled", "a quiet harbor", "the last train"]
        out = await asyncio.gather(
            *(svc.generate_content(s, False) for s in seeds))
        await svc.stop()
        return out

    contents = asyncio.run(run())
    svc.backend.prompt_gen.generate_batch = orig
    # one coalesced decode batch carried all three seeds (the queue may
    # split under scheduling jitter, but must not degrade to singletons)
    decode_batches = [b for b in seen_batches if len(b) > 1]
    assert decode_batches, f"no coalescing happened: {seen_batches}"
    assert sum(len(b) for b in seen_batches) == 3
    for content in contents:
        assert content.prompt_text and content.image is not None


@pytest.mark.slow
def test_soak_run_smoke():
    """The sustained-serving soak harness (bench.py:soak_run) drives N
    rounds of content generation under continuous guess pressure and
    returns latency samples — smoke-tested here at tiny config on CPU;
    the suite's `soak` entry reports p50/p99 from the same code path.

    slow (round 21): see test_concurrent_rounds_coalesce_prompt_decodes
    — the real-pipeline InferenceService build dominates; the harness
    code path itself is exercised by the bench suite's `soak` entry."""
    import asyncio

    from bench import soak_run
    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.service import InferenceService

    svc = InferenceService(test_config())
    elapsed, lats, errors = asyncio.run(soak_run(svc, rounds=2, workers=4))
    assert elapsed > 0
    assert len(lats) >= 4   # pressure loops actually scored guesses
    assert errors == 0
