"""Model zoo tests on CPU-JAX with tiny configs: shapes, determinism,
causality, and KV-cache parity (SURVEY.md §4 tier 1-2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.models import (
    ClipTextEncoder,
    GPT2LM,
    MiniLMEncoder,
    UNet,
    VAEDecoder,
    VAEEncoder,
)
from cassmantle_tpu.models.vae import postprocess_images
from cassmantle_tpu.models.weights import init_params


@pytest.fixture(scope="module")
def tiny(cfg):
    return cfg.models


def test_clip_text_shapes(tiny):
    model = ClipTextEncoder(tiny.clip_text)
    ids = jnp.array([[1, 5, 9, 2, 0, 0, 0, 0]], dtype=jnp.int32)
    params = init_params(model, 0, ids)
    out = model.apply(params, ids)
    assert out["hidden"].shape == (1, 8, tiny.clip_text.hidden_size)
    assert out["pooled"].shape == (1, tiny.clip_text.hidden_size)
    # deterministic
    out2 = model.apply(params, ids)
    np.testing.assert_allclose(out["hidden"], out2["hidden"])


def test_clip_text_causal(tiny):
    """Changing a later token must not affect earlier hidden states."""
    model = ClipTextEncoder(tiny.clip_text)
    ids_a = jnp.array([[1, 5, 9, 2]], dtype=jnp.int32)
    ids_b = jnp.array([[1, 5, 9, 7]], dtype=jnp.int32)
    params = init_params(model, 0, ids_a)
    ha = model.apply(params, ids_a)["hidden"]
    hb = model.apply(params, ids_b)["hidden"]
    np.testing.assert_allclose(ha[:, :3], hb[:, :3], atol=1e-5)
    assert not np.allclose(ha[:, 3], hb[:, 3])


def test_unet_shapes_and_determinism(tiny):
    model = UNet(tiny.unet)
    lat = jnp.ones((2, 16, 16, 4), dtype=jnp.float32)
    t = jnp.array([10, 20], dtype=jnp.int32)
    ctx = jnp.ones((2, 8, tiny.unet.context_dim), dtype=jnp.float32)
    params = init_params(model, 0, lat, t, ctx)
    out = model.apply(params, lat, t, ctx)
    assert out.shape == lat.shape
    assert out.dtype == jnp.float32
    assert np.isfinite(np.asarray(out)).all()
    out2 = model.apply(params, lat, t, ctx)
    np.testing.assert_allclose(out, out2)


def test_unet_timestep_sensitivity(tiny):
    model = UNet(tiny.unet)
    lat = jnp.ones((1, 16, 16, 4), dtype=jnp.float32)
    ctx = jnp.ones((1, 8, tiny.unet.context_dim), dtype=jnp.float32)
    params = init_params(model, 0, lat, jnp.array([0]), ctx)
    o1 = model.apply(params, lat, jnp.array([0]), ctx)
    o2 = model.apply(params, lat, jnp.array([500]), ctx)
    assert not np.allclose(o1, o2)


def test_vae_decoder_shapes(tiny):
    model = VAEDecoder(tiny.vae)
    lat = jnp.zeros((1, 8, 8, 4), dtype=jnp.float32)
    params = init_params(model, 0, lat)
    out = model.apply(params, lat)
    # channel_mults has 2 levels -> one 2x upsample
    assert out.shape == (1, 16, 16, 3)
    u8 = postprocess_images(out)
    assert u8.dtype == jnp.uint8


def test_vae_encoder_decoder_roundtrip_shapes(tiny):
    enc = VAEEncoder(tiny.vae)
    img = jnp.zeros((1, 16, 16, 3), dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    params = init_params(enc, 0, img, rng)
    z = enc.apply(params, img, rng)
    assert z.shape == (1, 8, 8, 4)


def test_gpt2_forward_and_causality(tiny):
    model = GPT2LM(tiny.gpt2)
    ids = jnp.array([[3, 7, 11, 2, 5]], dtype=jnp.int32)
    params = init_params(model, 0, ids)
    logits = model.apply(params, ids)
    assert logits.shape == (1, 5, tiny.gpt2.vocab_size)
    ids2 = ids.at[0, 4].set(9)
    logits2 = model.apply(params, ids2)
    np.testing.assert_allclose(logits[:, :4], logits2[:, :4], atol=1e-4)


def test_gpt2_kv_cache_matches_full_forward(tiny):
    """Greedy path correctness: prefill+decode_step == full forward."""
    model = GPT2LM(tiny.gpt2)
    max_len = 12
    ids = jnp.array([[3, 7, 11, 2, 0, 0]], dtype=jnp.int32)  # padded to 6
    prompt_len = jnp.array([4], dtype=jnp.int32)
    params = init_params(model, 0, ids)

    last_logits, cache = model.apply(
        params, ids, prompt_len, max_len, method=GPT2LM.prefill
    )
    full_logits = model.apply(params, ids[:, :4])
    np.testing.assert_allclose(
        last_logits, full_logits[:, 3], rtol=2e-4, atol=2e-4
    )

    # decode one step with the cache vs running the extended sequence
    next_tok = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
    valid = (jnp.arange(max_len)[None, :] < 4) | (
        jnp.arange(max_len)[None, :] == 4
    )
    step_logits, cache = model.apply(
        params, next_tok, jnp.int32(4), cache, valid,
        method=GPT2LM.decode_step,
    )
    ext = jnp.concatenate([ids[:, :4], next_tok[:, None]], axis=1)
    full_ext = model.apply(params, ext)
    np.testing.assert_allclose(
        step_logits, full_ext[:, 4], rtol=2e-4, atol=2e-4
    )


def test_minilm_embeddings(tiny):
    model = MiniLMEncoder(tiny.minilm)
    ids = jnp.array([[5, 9, 2, 0], [7, 0, 0, 0]], dtype=jnp.int32)
    mask = jnp.array([[1, 1, 1, 0], [1, 0, 0, 0]], dtype=jnp.int32)
    params = init_params(model, 0, ids, mask)
    emb = model.apply(params, ids, mask)
    assert emb.shape == (2, tiny.minilm.hidden_size)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(emb), axis=-1), 1.0, atol=1e-4
    )
    # padding must not influence the embedding
    ids_b = ids.at[0, 3].set(99)
    emb_b = model.apply(params, ids_b, mask)
    np.testing.assert_allclose(emb[0], emb_b[0], atol=1e-5)
