"""Numerical parity vs the reference torch implementations.

The strongest available cross-check in a zero-egress container: the
transformers library (installed) IS the library whose checkpoints this
framework loads, so instantiating its model classes with random weights,
converting their state_dicts through models/weights.py, and comparing
forward outputs validates BOTH the converters and our Flax architecture
math against the independent reference implementation — RoPE
conventions, GQA layout, CLIP causal masking, activation variants, norm
epsilons, pooling. All five families match to float32 roundoff
(~1e-7 at these dims); the tolerances below leave margin for platform
variation only. (diffusers is not installed, so the UNet/VAE sides are
covered by the manifest + published-param-total checks in
tests/test_manifests.py instead.)

This is what closed VERDICT r2's 'converters are only self-consistent'
finding numerically; it also caught the LayerNorm-epsilon and BERT
exact-gelu mismatches fixed alongside (published eps: GPT-2/CLIP 1e-5,
BERT 1e-12, Mistral RMS 1e-5, AutoencoderKL GroupNorm 1e-6).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from cassmantle_tpu.config import (  # noqa: E402
    ClipTextConfig,
    GPT2Config,
    MiniLMConfig,
    MistralConfig,
)
from cassmantle_tpu.models import (  # noqa: E402
    ClipTextEncoder,
    GPT2LM,
    MiniLMEncoder,
)
from cassmantle_tpu.models.weights import (  # noqa: E402
    convert_clip_text,
    convert_clip_vision,
    convert_gpt2,
    convert_minilm,
    convert_mistral,
)

ATOL = 5e-5


def sd_np(model):
    return {k: v.detach().numpy() for k, v in model.state_dict().items()}


def to_jax(tree):
    return jax.tree_util.tree_map(jnp.asarray, tree)


def assert_close(ours, theirs):
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=ATOL,
                               rtol=1e-4)


def test_gpt2_matches_transformers():
    from transformers import GPT2Config as HFConfig, GPT2Model

    torch.manual_seed(0)
    hf = GPT2Model(HFConfig(vocab_size=128, n_embd=64, n_layer=2,
                            n_head=4, n_positions=64)).eval()
    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    with torch.no_grad():
        hidden = hf(torch.tensor(ids)).last_hidden_state.numpy()
    ref_logits = hidden @ sd_np(hf)["wte.weight"].T

    ours = GPT2LM(GPT2Config(vocab_size=128, hidden_size=64, num_layers=2,
                             num_heads=4, max_positions=64,
                             dtype="float32"))
    params = to_jax(convert_gpt2(sd_np(hf), 2, 64))
    assert_close(ours.apply(params, jnp.asarray(ids)), ref_logits)


def test_minilm_matches_transformers():
    from transformers import BertConfig, BertModel

    torch.manual_seed(0)
    hf = BertModel(BertConfig(
        vocab_size=100, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32,
        attn_implementation="eager")).eval()
    ids = np.random.default_rng(1).integers(0, 100, (2, 10))
    mask = np.ones((2, 10), np.int64)
    mask[1, 7:] = 0
    with torch.no_grad():
        hidden = hf(torch.tensor(ids),
                    attention_mask=torch.tensor(mask)).last_hidden_state
    # reference mean-pool + normalize (the scorer pipeline's pooling)
    w = mask[..., None].astype(np.float64)
    pooled = (hidden.numpy() * w).sum(1) / (w.sum(1) + 1e-9)
    pooled = pooled / (np.linalg.norm(pooled, axis=-1, keepdims=True)
                       + 1e-9)

    ours = MiniLMEncoder(MiniLMConfig(
        vocab_size=100, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_positions=32, dtype="float32"))
    params = to_jax(convert_minilm(sd_np(hf), 2))
    assert_close(ours.apply(params, jnp.asarray(ids), jnp.asarray(mask)),
                 pooled)


def test_clip_text_matches_transformers():
    from transformers import CLIPTextConfig as HFConfig, CLIPTextModel

    torch.manual_seed(0)
    # eos_token_id must be the fabricated vocab's EOT (real CLIP: 49407,
    # the max id — our argmax pooling and HF's first-EOS pooling agree
    # because pad==eos, and argmax returns the FIRST max position)
    hf = CLIPTextModel(HFConfig(
        vocab_size=99, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=16, eos_token_id=98)).eval()
    ids = np.random.default_rng(2).integers(0, 98, (2, 9))
    ids[:, -1] = 98  # highest id last = EOT position for our pooling
    with torch.no_grad():
        hidden = hf(torch.tensor(ids)).last_hidden_state.numpy()

    ours = ClipTextEncoder(ClipTextConfig(
        vocab_size=99, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, max_positions=16))
    params = to_jax(convert_clip_text(sd_np(hf), 2))
    out = ours.apply(params, jnp.asarray(ids))
    assert_close(out["hidden"], hidden)  # causal mask + quick_gelu + eps
    with torch.no_grad():
        pooled = hf(torch.tensor(ids)).pooler_output.numpy()
    assert_close(out["pooled"], pooled)  # EOT-argmax pooling


def test_clip_bigg_style_matches_transformers():
    """SDXL's second tower (OpenCLIP bigG) uses EXACT gelu, not ViT-L's
    quick_gelu — ClipTextConfig.hidden_act selects it and must match the
    transformers model at hidden_act='gelu'."""
    from transformers import CLIPTextConfig as HFConfig, CLIPTextModel

    torch.manual_seed(1)
    hf = CLIPTextModel(HFConfig(
        vocab_size=99, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=16, hidden_act="gelu")).eval()
    ids = np.random.default_rng(5).integers(0, 98, (2, 9))
    ids[:, -1] = 98
    with torch.no_grad():
        hidden = hf(torch.tensor(ids)).last_hidden_state.numpy()

    ours = ClipTextEncoder(ClipTextConfig(
        vocab_size=99, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, max_positions=16, hidden_act="gelu"))
    params = to_jax(convert_clip_text(sd_np(hf), 2))
    assert_close(ours.apply(params, jnp.asarray(ids))["hidden"], hidden)


def test_clip_vision_matches_transformers():
    from transformers import CLIPConfig as HFConfig, CLIPModel

    from cassmantle_tpu.models.clip_vision import (
        ClipVisionConfig,
        ClipVisionEncoder,
    )

    torch.manual_seed(0)
    hf = CLIPModel(HFConfig(
        projection_dim=24,
        text_config=dict(
            vocab_size=99, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=16, projection_dim=24),
        vision_config=dict(
            hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, image_size=32, patch_size=8,
            projection_dim=24))).eval()
    pix = np.random.default_rng(3).standard_normal(
        (2, 3, 32, 32)).astype(np.float32)
    with torch.no_grad():
        feats = hf.get_image_features(torch.tensor(pix)).numpy()
    feats = feats / np.linalg.norm(feats, axis=-1, keepdims=True)

    ours = ClipVisionEncoder(ClipVisionConfig(
        image_size=32, patch_size=8, hidden_size=32, intermediate_size=64,
        num_layers=2, num_heads=4, projection_dim=24))
    params = to_jax(convert_clip_vision(sd_np(hf), 2))
    out = ours.apply(params, jnp.asarray(np.transpose(pix, (0, 2, 3, 1))))
    assert_close(out, feats)


def _our_decode(model, params, ids_np, prompt_len, max_new, vocab):
    from cassmantle_tpu.ops.decode import greedy_decode, make_apply_pair

    toks, n = greedy_decode(
        make_apply_pair(model), params, jnp.asarray(ids_np),
        jnp.asarray([prompt_len], jnp.int32), jax.random.PRNGKey(0),
        max_new, vocab)  # vocab = unreachable eos -> no early stop
    return np.asarray(toks[0])


def test_gpt2_decode_matches_transformers_generate():
    """The KV-cache serving decode (prefill + scan) reproduces
    transformers' own greedy generate loop token for token — the
    end-to-end seal on the text-serving path (positions, cache
    indexing, and mask handling included)."""
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel

    torch.manual_seed(0)
    hf = GPT2LMHeadModel(HFConfig(vocab_size=128, n_embd=64, n_layer=2,
                                  n_head=4, n_positions=64)).eval()
    ids = np.random.default_rng(6).integers(1, 128, (1, 7))
    with torch.no_grad():
        out = hf.generate(torch.tensor(ids), max_new_tokens=6,
                          do_sample=False, pad_token_id=0)
    ref = out[0, 7:].numpy()

    sd = {k.removeprefix("transformer."): v.detach().numpy()
          for k, v in hf.state_dict().items()
          if k.startswith("transformer.")}
    cfg = GPT2Config(vocab_size=128, hidden_size=64, num_layers=2,
                     num_heads=4, max_positions=64, dtype="float32")
    ours = _our_decode(GPT2LM(cfg), to_jax(convert_gpt2(sd, 2, 64)),
                       ids, 7, 6, 128)
    np.testing.assert_array_equal(ours, ref)


def test_mistral_decode_matches_transformers_generate():
    from transformers import (
        MistralConfig as HFConfig,
        MistralForCausalLM,
    )

    from cassmantle_tpu.models.mistral import MistralLM

    torch.manual_seed(0)
    hf = MistralForCausalLM(HFConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, sliding_window=64,
        tie_word_embeddings=False, rms_norm_eps=1e-5,
        attn_implementation="eager")).eval()
    ids = np.random.default_rng(7).integers(3, 256, (1, 7))
    with torch.no_grad():
        # eos disabled on BOTH sides (ours uses the unreachable
        # sentinel): the comparison is the raw greedy trajectory
        out = hf.generate(torch.tensor(ids), max_new_tokens=6,
                          do_sample=False, pad_token_id=0,
                          eos_token_id=None)
    ref = out[0, 7:].numpy()

    cfg = dataclasses.replace(MistralConfig.tiny(), sliding_window=64)
    params = to_jax(convert_mistral(
        {k: v.detach().numpy() for k, v in hf.state_dict().items()}, 2))
    ours = _our_decode(MistralLM(cfg), params, ids, 7, 6, 256)
    np.testing.assert_array_equal(ours, ref)


def test_mistral_matches_transformers():
    from transformers import (
        MistralConfig as HFConfig,
        MistralForCausalLM,
    )

    from cassmantle_tpu.models.mistral import MistralLM

    torch.manual_seed(0)
    hf = MistralForCausalLM(HFConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, sliding_window=64,
        tie_word_embeddings=False, rms_norm_eps=1e-5,
        attn_implementation="eager")).eval()
    ids = np.random.default_rng(4).integers(0, 256, (2, 12))
    with torch.no_grad():
        logits = hf(torch.tensor(ids)).logits.numpy()

    cfg = dataclasses.replace(MistralConfig.tiny(), sliding_window=64)
    params = to_jax(convert_mistral(sd_np(hf), 2))
    assert_close(MistralLM(cfg).apply(params, jnp.asarray(ids)), logits)


def test_clip_similarity_harness_matches_transformers():
    """The FULL eval/clip_parity.py metric path — text pooling, text
    projection, image preprocessing, vision tower + visual projection,
    both normalizations, dot product — against torch CLIPModel with the
    same random weights (VERDICT r5 'Next round' #3: prove the
    CLIP-gate metric implementation now, calibrate with real weights
    later). Images are fed at the vision tower's native size so both
    sides see the same pixels."""
    from transformers import CLIPConfig as HFConfig, CLIPModel

    from cassmantle_tpu.eval.clip_parity import ClipSimilarityHarness
    from cassmantle_tpu.models.clip_vision import (
        CLIP_IMAGE_MEAN,
        CLIP_IMAGE_STD,
        ClipVisionConfig,
    )
    from cassmantle_tpu.models.weights import (
        convert_clip_text_projection,
    )

    torch.manual_seed(0)
    hf = CLIPModel(HFConfig(
        projection_dim=24,
        text_config=dict(
            vocab_size=99, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=16, eos_token_id=98,
            projection_dim=24),
        vision_config=dict(
            hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, image_size=32, patch_size=8,
            projection_dim=24))).eval()
    sd = sd_np(hf)

    harness = ClipSimilarityHarness(
        text_cfg=ClipTextConfig(
            vocab_size=99, hidden_size=32, intermediate_size=64,
            num_layers=2, num_heads=4, max_positions=16),
        vision_cfg=ClipVisionConfig(
            image_size=32, patch_size=8, hidden_size=32,
            intermediate_size=64, num_layers=2, num_heads=4,
            projection_dim=24),
        pad_len=16)
    # same random weights on both sides: override the harness's
    # random-init params with the converted torch tree
    params = {
        "text": to_jax(convert_clip_text(sd, 2)),
        "vision": to_jax(convert_clip_vision(sd, 2)),
        "proj": jnp.asarray(convert_clip_text_projection(sd)),
    }

    rng = np.random.default_rng(9)
    ids = rng.integers(0, 98, (3, 9)).astype(np.int32)
    ids[:, -1] = 98  # EOT position for both poolings
    images = rng.integers(0, 256, (3, 32, 32, 3)).astype(np.uint8)

    ours = np.asarray(harness._jit_sim(
        params, jnp.asarray(ids), jnp.asarray(images)))

    # torch side: identical preprocessing (images are already at the
    # tower's size, so resize is identity), then the public
    # get_*_features path
    pix = images.astype(np.float32) / 255.0
    pix = (pix - np.asarray(CLIP_IMAGE_MEAN)) / np.asarray(CLIP_IMAGE_STD)
    pix = np.transpose(pix, (0, 3, 1, 2))
    with torch.no_grad():
        temb = hf.get_text_features(torch.tensor(ids.astype(np.int64)))
        vemb = hf.get_image_features(torch.tensor(pix))
    temb = temb.numpy()
    temb = temb / (np.linalg.norm(temb, axis=-1, keepdims=True) + 1e-8)
    vemb = vemb.numpy()
    vemb = vemb / np.linalg.norm(vemb, axis=-1, keepdims=True)
    ref = (temb * vemb).sum(-1)

    np.testing.assert_allclose(ours, ref, atol=1e-4, rtol=1e-3)
