"""Parallelism tests on the 8-virtual-CPU-device mesh (SURVEY.md §4 tier 3):
real XLA collectives, no cluster."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from cassmantle_tpu.config import MeshConfig
from cassmantle_tpu.models.unet import UNet
from cassmantle_tpu.models.weights import init_params
from cassmantle_tpu.ops.attention import xla_attention
from cassmantle_tpu.parallel.mesh import make_mesh, resolve_axis_sizes
from cassmantle_tpu.parallel.ring import ring_attention
from cassmantle_tpu.parallel.sharding import shard_params
from cassmantle_tpu.parallel.train import DiffusionTrainer


def test_resolve_axis_sizes():
    # order matches axis_names: (dp, pp, tp, sp, ep)
    assert resolve_axis_sizes(MeshConfig(), 8) == [8, 1, 1, 1, 1]
    assert resolve_axis_sizes(MeshConfig(dp=-1, tp=2), 8) == [4, 1, 2, 1, 1]
    assert resolve_axis_sizes(
        MeshConfig(dp=2, tp=2, sp=2), 8) == [2, 1, 2, 2, 1]
    assert resolve_axis_sizes(
        MeshConfig(dp=-1, pp=2, ep=2), 8) == [2, 2, 1, 1, 2]


def test_make_mesh_shapes():
    mesh = make_mesh(MeshConfig(dp=2, tp=2, sp=2))
    assert dict(mesh.shape) == {"dp": 2, "pp": 1, "tp": 2, "sp": 2, "ep": 1}
    mesh = make_mesh(MeshConfig())
    assert mesh.shape["dp"] == 8


def test_ring_attention_matches_reference():
    mesh = make_mesh(MeshConfig(dp=1, tp=1, sp=8))
    b, s, h, d = 2, 64, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    ref = xla_attention(q, k, v)
    out = ring_attention(q, k, v, mesh, axis_name="sp")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_ring_attention_jit_under_mesh():
    mesh = make_mesh(MeshConfig(dp=2, tp=1, sp=4))
    b, s, h, d = 1, 32, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    f = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))
    out = f(q, k, v)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_tp_sharded_unet_matches_single_device(cfg):
    """Forward parity: tp-sharded params must give the same output."""
    ucfg = cfg.models.unet
    model = UNet(ucfg)
    lat = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 16, 4))
    t = jnp.array([3, 7], dtype=jnp.int32)
    ctx = jax.random.normal(jax.random.PRNGKey(1), (2, 8, ucfg.context_dim))
    params = init_params(model, 0, lat, t, ctx)
    ref = model.apply(params, lat, t, ctx)

    mesh = make_mesh(MeshConfig(dp=2, tp=4, sp=1))
    sharded = shard_params(params, mesh)
    lat_s = jax.device_put(lat, NamedSharding(mesh, P("dp")))
    out = jax.jit(model.apply)(sharded, lat_s, t, ctx)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4
    )


def test_tp_params_actually_sharded(cfg):
    ucfg = cfg.models.unet
    model = UNet(ucfg)
    lat = jnp.zeros((1, 16, 16, 4))
    t = jnp.zeros((1,), jnp.int32)
    ctx = jnp.zeros((1, 8, ucfg.context_dim))
    params = init_params(model, 0, lat, t, ctx)
    mesh = make_mesh(MeshConfig(dp=2, tp=4, sp=1))
    sharded = shard_params(params, mesh)
    kernel = sharded["params"]["down_0_attn_0"]["block_0"]["self_attn"][
        "qkv"
    ]["kernel"]
    spec = kernel.sharding.spec
    assert tuple(spec) == (None, "tp"), spec
    # conv kernels replicated
    conv = sharded["params"]["conv_in"]["kernel"]
    assert tuple(conv.sharding.spec) in ((), (None,) * conv.ndim)


def test_train_step_runs_and_learns(cfg):
    mesh = make_mesh(MeshConfig(dp=2, tp=2, sp=2))
    trainer = DiffusionTrainer(cfg, mesh, lr=1e-3)
    b = 4
    batch = {
        "latents": jax.random.normal(jax.random.PRNGKey(0), (b, 16, 16, 4)),
        "context": jax.random.normal(
            jax.random.PRNGKey(1), (b, 8, cfg.models.unet.context_dim)
        ),
    }
    batch = trainer.shard_batch(batch)
    params, opt_state = trainer.init_state(batch)
    losses = []
    rng = jax.random.PRNGKey(2)
    for i in range(8):
        rng, sub = jax.random.split(rng)
        params, opt_state, loss = trainer.step(
            params, opt_state, batch, sub
        )
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    # optimizing the same batch must reduce loss
    assert np.mean(losses[-2:]) < np.mean(losses[:2])


def test_ulysses_attention_matches_reference():
    from cassmantle_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(MeshConfig(dp=1, tp=1, sp=8))
    b, s, h, d = 2, 64, 8, 16
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    ref = xla_attention(q, k, v)
    out = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, mesh)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    from cassmantle_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(MeshConfig(dp=1, tp=1, sp=8))
    q = jnp.zeros((1, 16, 6, 8))  # 6 heads, sp=8
    with pytest.raises(AssertionError):
        ulysses_attention(q, q, q, mesh)


def test_train_step_remat_matches(cfg):
    """jax.checkpoint trades FLOPs for memory without changing the math."""
    mesh = make_mesh(MeshConfig(dp=2, tp=2, sp=2))
    b = 4
    batch = {
        "latents": jax.random.normal(jax.random.PRNGKey(0), (b, 16, 16, 4)),
        "context": jax.random.normal(
            jax.random.PRNGKey(1), (b, 8, cfg.models.unet.context_dim)
        ),
    }
    plain = DiffusionTrainer(cfg, mesh, lr=1e-3)
    remat = DiffusionTrainer(cfg, mesh, lr=1e-3, remat=True)
    sb = plain.shard_batch(batch)
    p0, o0 = plain.init_state(sb)
    p1, o1 = remat.init_state(sb)
    _, _, l0 = plain.step(p0, o0, sb, jax.random.PRNGKey(3))
    _, _, l1 = remat.step(p1, o1, sb, jax.random.PRNGKey(3))
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)


def test_ring_attention_causal_matches_reference():
    """Contiguous causal ring attention (the schedule="contiguous"
    oracle) vs full-sequence triangular-masked reference."""
    mesh = make_mesh(MeshConfig(dp=1, tp=1, sp=8))
    b, s, h, d = 2, 64, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    mask = jnp.tril(jnp.ones((s, s), bool))
    ref = xla_attention(q, k, v, mask=mask)
    out = ring_attention(q, k, v, mesh, axis_name="sp", causal=True,
                         schedule="contiguous")
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_ring_attention_causal_defaults_to_zigzag():
    """ring_attention(causal=True) must route to the load-balanced
    zigzag schedule when S divides 2n (VERDICT r4 #8): dispatch
    observed directly, and the result still matches the masked
    reference."""
    from cassmantle_tpu.parallel import ring as ring_mod

    mesh = make_mesh(MeshConfig(dp=1, tp=1, sp=8))
    b, s, h, d = 1, 64, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    called = []
    orig = ring_mod.zigzag_ring_attention
    ring_mod.zigzag_ring_attention = (
        lambda *a, **kw: called.append(1) or orig(*a, **kw))
    try:
        out = ring_attention(q, k, v, mesh, axis_name="sp", causal=True)
    finally:
        ring_mod.zigzag_ring_attention = orig
    assert called, "causal ring did not dispatch to zigzag"
    mask = jnp.tril(jnp.ones((s, s), bool))
    ref = xla_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )
    # sequences that divide n but not 2n must still work (contiguous
    # fallback): S=8 over sp=8 -> one row per device
    q8, k8, v8 = q[:, :8], k[:, :8], v[:, :8]
    out8 = ring_attention(q8, k8, v8, mesh, axis_name="sp", causal=True)
    ref8 = xla_attention(q8, k8, v8, mask=jnp.tril(jnp.ones((8, 8), bool)))
    np.testing.assert_allclose(
        np.asarray(out8), np.asarray(ref8), atol=1e-5, rtol=1e-5
    )


def test_ulysses_attention_causal_matches_reference():
    from cassmantle_tpu.parallel.ulysses import ulysses_attention

    mesh = make_mesh(MeshConfig(dp=2, tp=1, sp=4))
    b, s, h, d = 1, 32, 4, 8
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    mask = jnp.tril(jnp.ones((s, s), bool))
    ref = xla_attention(q, k, v, mask=mask)
    out = ulysses_attention(q, k, v, mesh, axis_name="sp", causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_zigzag_permute_roundtrip():
    from cassmantle_tpu.parallel.ring import (
        zigzag_permute,
        zigzag_unpermute,
    )

    x = jnp.arange(2 * 16 * 3).reshape(2, 16, 3)
    z = zigzag_permute(x, n=4)
    # device 0's shard (first 4 rows) = chunks c0 and c7
    np.testing.assert_array_equal(np.asarray(z[:, :2]),
                                  np.asarray(x[:, :2]))
    np.testing.assert_array_equal(np.asarray(z[:, 2:4]),
                                  np.asarray(x[:, 14:16]))
    np.testing.assert_array_equal(np.asarray(zigzag_unpermute(z, n=4)),
                                  np.asarray(x))


def test_zigzag_ring_attention_matches_causal_reference():
    """Load-balanced causal ring attention vs triangular-masked
    reference — the schedule that halves critical-path attention
    compute at long context."""
    from cassmantle_tpu.parallel.ring import zigzag_ring_attention

    mesh = make_mesh(MeshConfig(dp=1, tp=1, sp=8))
    b, s, h, d = 2, 64, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    mask = jnp.tril(jnp.ones((s, s), bool))
    ref = xla_attention(q, k, v, mask=mask)
    out = zigzag_ring_attention(q, k, v, mesh, axis_name="sp")
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_zigzag_ring_attention_sp2():
    from cassmantle_tpu.parallel.ring import zigzag_ring_attention

    mesh = make_mesh(MeshConfig(dp=4, tp=1, sp=2))
    b, s, h, d = 1, 16, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(10), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, h, d))
    v = jax.random.normal(ks[2], (b, s, h, d))
    mask = jnp.tril(jnp.ones((s, s), bool))
    ref = xla_attention(q, k, v, mask=mask)
    out = zigzag_ring_attention(q, k, v, mesh, axis_name="sp")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_spatially_partitioned_serving_matches_unsharded():
    """sp-axis spatial partitioning of the SERVING denoise (SURVEY §5.7's
    1024²+ scale-up path): with latents constrained to P("dp","sp"),
    GSPMD halo-exchanges the convs and reshards the attention flattens.

    Parity is asserted at the DENOISE-STEP level with fp tolerance, not
    on final uint8 images: spatial partitioning changes fp reduction
    order (legal, ~1e-6), and the DDIM update's 1/sqrt(alpha_t)
    amplification compounds such perturbations exponentially across
    steps — under RANDOM weights (no trained smoothness) the end images
    decorrelate from roundoff alone, so whole-pipeline bit-parity would
    test compiler determinism, not partitioning correctness. The full
    sharded generate() still runs end to end (shape/finiteness).
    """
    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    cfg = test_config()
    ref_pipe = Text2ImagePipeline(cfg)
    mesh = make_mesh(MeshConfig(dp=2, tp=1, sp=2),
                     devices=jax.devices()[:4])
    sp_pipe = Text2ImagePipeline(cfg, mesh=mesh, share_params_with=ref_pipe)

    # one full denoise forward, spatially constrained vs not, same inputs
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cassmantle_tpu.serving.pipeline import spatially_shard_latents

    lat = jax.random.normal(jax.random.PRNGKey(21), (2, 32, 32, 4))
    ts = jnp.asarray([3, 7])
    ctx = jax.random.normal(
        jax.random.PRNGKey(22),
        (2, 8, cfg.models.unet.context_dim))
    ref = jax.jit(ref_pipe.unet_apply)(
        ref_pipe.unet_params, lat, ts, ctx)
    batch = NamedSharding(mesh, P("dp"))

    def sharded(p, l, t, c):
        return sp_pipe.unet_apply(p, spatially_shard_latents(l, mesh),
                                  t, c)

    out = jax.jit(sharded, in_shardings=(None, batch, batch, batch))(
        sp_pipe.unet_params, lat, ts, ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)

    # the whole sharded pipeline executes (halo exchanges, resharding,
    # VAE, postprocess) and produces well-formed images
    imgs = sp_pipe.generate(["a lighthouse", "a harbor"], seed=11)
    assert imgs.shape == (2, cfg.sampler.image_size,
                          cfg.sampler.image_size, 3)
    assert imgs.dtype == np.uint8
    assert int(imgs.std()) > 0  # not a constant fill


def test_spatially_partitioned_sdxl_matches_unsharded():
    """SDXL variant of the spatial-partitioning check: denoise-step
    parity under the sp constraint (see the SD1.5 test above for why
    uint8 end-image comparison is not meaningful under random
    weights), plus a full sharded generate()."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cassmantle_tpu.config import test_sdxl_config
    from cassmantle_tpu.serving.pipeline import spatially_shard_latents
    from cassmantle_tpu.serving.sdxl import SDXLPipeline

    cfg = test_sdxl_config()
    ref_pipe = SDXLPipeline(cfg)
    mesh = make_mesh(MeshConfig(dp=2, tp=1, sp=2),
                     devices=jax.devices()[:4])
    sp_pipe = SDXLPipeline(cfg, mesh=mesh)

    ucfg = cfg.models.unet
    lat = jax.random.normal(jax.random.PRNGKey(31), (2, 32, 32, 4))
    ts = jnp.asarray([3, 7])
    ctx = jax.random.normal(jax.random.PRNGKey(32),
                            (2, 8, ucfg.context_dim))
    add = jax.random.normal(jax.random.PRNGKey(33),
                            (2, ucfg.addition_embed_dim))
    ref = jax.jit(ref_pipe.unet_apply)(
        ref_pipe.unet_params, lat, ts, ctx, add)
    batch = NamedSharding(mesh, P("dp"))

    def sharded(p, l, t, c, a):
        return sp_pipe.unet_apply(p, spatially_shard_latents(l, mesh),
                                  t, c, a)

    out = jax.jit(sharded,
                  in_shardings=(None, batch, batch, batch, batch))(
        sp_pipe.unet_params, lat, ts, ctx, add)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)

    imgs = sp_pipe.generate(["a night train", "an orchard"], seed=12)
    assert imgs.shape[0] == 2 and imgs.dtype == np.uint8
    assert int(imgs.std()) > 0
