"""Test env: force JAX onto a virtual 8-device CPU mesh.

This is the multi-device-without-a-cluster strategy from SURVEY.md §4: all
collective/sharding tests exercise real XLA collectives on 8 host devices; the
real-chip path is covered by bench.py and the driver's dryrun.
"""

# 8 virtual CPU devices + raised collective timeouts (on few-core hosts
# the devices' programs serialize past XLA's default 40 s rendezvous
# timeout), pinned hermetically: the suite must never initialize an
# accelerator-plugin backend — that blocks forever when the tunnel
# behind it is down. The ordering rules live in pin_cpu_platform.
from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

pin_cpu_platform(virtual_devices=True)

import jax  # noqa: E402, F401

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402

from cassmantle_tpu.config import test_config  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (no pytest-asyncio here)."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(func(**kwargs))
        return True
    return None


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: async test (built-in runner)")


@pytest.fixture(scope="session")
def cfg():
    return test_config()
