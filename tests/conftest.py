"""Test env: force JAX onto a virtual 8-device CPU mesh.

This is the multi-device-without-a-cluster strategy from SURVEY.md §4: all
collective/sharding tests exercise real XLA collectives on 8 host devices; the
real-chip path is covered by bench.py and the driver's dryrun.
"""

import os  # noqa: F401  (kept for tests that monkeypatch env)

# Raised collective timeouts: on few-core hosts the 8 virtual devices'
# programs serialize and XLA's default 40 s termination timeout kills the
# process mid-rendezvous. The helper is jax-free, so this import cannot
# initialize a backend before the flags land.
from cassmantle_tpu.utils.xla_flags import (
    COLLECTIVE_TIMEOUT_FLAGS,
    VIRTUAL_8_DEVICE_FLAG,
    append_xla_flags,
)

append_xla_flags(VIRTUAL_8_DEVICE_FLAG, *COLLECTIVE_TIMEOUT_FLAGS)

import jax  # noqa: E402

# The environment may pin JAX_PLATFORMS to a TPU plugin (e.g. axon); the
# config override below beats the env var and forces the 8 virtual CPU
# devices for every test.
jax.config.update("jax_platform_name", "cpu")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402

from cassmantle_tpu.config import test_config  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (no pytest-asyncio here)."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(func(**kwargs))
        return True
    return None


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: async test (built-in runner)")


@pytest.fixture(scope="session")
def cfg():
    return test_config()
