"""Test env: force JAX onto a virtual 8-device CPU mesh.

This is the multi-device-without-a-cluster strategy from SURVEY.md §4: all
collective/sharding tests exercise real XLA collectives on 8 host devices; the
real-chip path is covered by bench.py and the driver's dryrun.
"""

# 8 virtual CPU devices + raised collective timeouts (on few-core hosts
# the devices' programs serialize past XLA's default 40 s rendezvous
# timeout), pinned hermetically: the suite must never initialize an
# accelerator-plugin backend — that blocks forever when the tunnel
# behind it is down. The ordering rules live in pin_cpu_platform.
from cassmantle_tpu.utils.xla_flags import pin_cpu_platform

pin_cpu_platform(virtual_devices=True)

import jax  # noqa: E402, F401

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402

from cassmantle_tpu.config import test_config  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (no pytest-asyncio here)."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(func(**kwargs))
        return True
    return None


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: async test (built-in runner)")


# -- test tiers (VERDICT r5 weak #6: whole-suite doesn't fit a short ---------
# verification window). Two module-level tiers, assigned centrally here so
# the map is one place, not 37 pytestmark lines:
#
# - ``fast``: the quick whole-repo smoke — every subsystem covered (engine,
#   server, ops incl. the Pallas kernels, models, serving, native store,
#   spell/text, parity), minutes not tens of minutes. Run:
#       JAX_PLATFORMS=cpu pytest -q -m fast
# - ``slow``: the wall-clock hogs (multi-minute compile/e2e paths) that
#   the tier-1 `-m 'not slow'` run excludes so the default tier finishes
#   inside its timeout on small hosts. They still run in a full
#   un-filtered `pytest` on capable machines.
#
# Times that justified the split are per-module isolated runs on a 2-core
# host; see ROADMAP.md for the tier commands.

FAST_MODULES = frozenset({
    "test_aux", "test_bench_harness",
    # bench regression sentinel + device/cost observability (ISSUE 14):
    # the bench_diff verdict grammar is stdlib-fast; test_obs_device
    # compiles two tiny pipelines for the roofline acceptance smoke and
    # regenerates the cost-model artifact (pure eval_shape, ~20s) —
    # both are acceptance bars that must run in every quick sweep
    "test_bench_diff", "test_obs_device",
    "test_chaos",
    "test_check_concurrency",
    "test_check_jax", "test_check_metrics",
    # exception-flow/lifecycle lints + leak sentinel (ISSUE 19): the
    # golden violating/fixed pairs (PR 6 stop-strand, PR 8 cancel-
    # swallow), the repo-lints-clean gate, and the seeded-leak sentinel
    # units are stdlib-fast acceptance bars for the leak defense
    "test_check_lifecycle",
    # consistency distillation + few-step serving (ISSUE 15): the
    # toy-geometry training smoke, checkpoint-layout pin, the ≤8-
    # forwards acceptance counter, and the brownout few-step tier are
    # acceptance bars that must run in every quick sweep; the
    # real-geometry distill compile test inside the module is marked
    # slow per-test (the marker loop below keeps it out of `-m fast`)
    "test_distill",
    # zero-device guess scoring (ISSUE 16): the artifact drift gate,
    # the int8-parity pin over the full wordlist (~25s tiny-encoder
    # embed, shared module-scoped), and the zero-queue/zero-device
    # counter pin are acceptance bars that must run in every quick
    # sweep — a stale committed table or a fast path that silently
    # dispatches device work must fail fast
    "test_embed_table",
    "test_eval",
    "test_fabric", "test_fault_injection",
    "test_flash_attention", "test_frontend", "test_fused_conv",
    "test_game",
    # output-integrity sentinels + device-loss recovery (ISSUE 17): the
    # verdict/poison units, device-loss classifier and recovery state
    # machine, the queue fail-fast and per-member exception pins, the
    # scorer poison-never-cached bar, the prompt-path range sentinel,
    # and the short in-process loss drill are acceptance bars for the
    # robustness plane — whole module measured ~9s on a 2-core host
    # (module-scoped tiny-encoder and tiny-GPT2 fixtures)
    "test_integrity",
    "test_js_runtime", "test_layers_norm", "test_masking",
    "test_masking_agreement", "test_multihost",
    "test_native_store", "test_obs", "test_obs_cluster", "test_ops",
    # canary prober (ISSUE 18): in-process HTTP probes, no device work
    "test_prober",
    # overload control plane (ISSUE 13): limiter/ladder/priority units
    # plus the ~10s spawned-worker goodput smoke — the overload
    # acceptance bar must run in every quick sweep
    "test_overload",
    "test_pipeline",
    "test_pipeline_parallel", "test_samplers", "test_scoring",
    "test_server", "test_spell", "test_store", "test_store_parity",
    "test_supervisor", "test_utils", "test_weights",
    # deliberately NOT fast (stay in the default tier):
    # test_spec_decode and test_stages — heavyweight parity suites
    # whose coverage the fast smoke doesn't need twice (test_pipeline
    # smokes the decode path). test_stages compiles three
    # pipeline-sized jits (staged encode/step/decode + the monolithic
    # reference) but MUST stay in tier-1: staged-vs-monolithic
    # bit-parity is an acceptance bar, and the autouse lock sentinel
    # only guards the stage scheduler's lock hierarchy if the module
    # actually runs in the default sweep. test_spec_decode stays for
    # the same reason: greedy/spec bit-parity + the jit-sentinel
    # steady-state assertions are tier-1 acceptance bars (PR 5/7).
    # test_encprop follows the same pattern (round 16): it compiles
    # two tiny pipelines, but stride-1 bit-parity, the quality gate,
    # and the warmed-encprop-loop jit sentinel are acceptance bars
    # that MUST run in the default sweep; its secondary pipeline
    # smokes (kill switch, counters, batched-decoder equivalence,
    # composed/preset pipelines) live in test_encprop_serving (slow).
})

SLOW_MODULES = frozenset({
    "test_parallel",   # 8-device mesh collectives: ~6 min of compiles
    "test_sdxl",       # dual-tower pipeline compiles: ~3 min
    "test_cli",        # subprocess-per-test CLI runs: ~2.5 min
    "test_deepcache",  # paired full/shallow pipeline compiles: ~2 min
    "test_img2img",    # encoder + per-strength-bucket compiles: ~1.5 min
    "test_manifests",  # full converter grammars over manifests: ~1 min
    # multi-process fabric cluster runs (worker subprocesses + sustained
    # HTTP/WS load + the store-leader failover drill): ~15 s of pure
    # wall clock that the per-component fast-tier coverage in
    # test_fabric already smoke-tests in-process
    "test_fabric_cluster",
    # the seeded chaos drill smoke: multi-process fabric phases (store
    # spawns + worker subprocesses + SIGTERM handoff) beside
    # test_fabric_cluster; the fast in-process versions of every
    # behavior live in test_chaos / test_fault_injection /
    # test_chaos_recovery
    "test_chaos_drill",
    # moved to slow at round 14: the default tier outgrew its tier-1
    # window on a 2-core host (the fabric + cluster-obs suites grew it
    # past ~900s vs the 870s budget) and was alphabetically truncating
    # its own tail — exactly what this split exists to prevent. Their
    # tier-1 coverage is duplicated: test_weights pins every torch
    # converter; test_spec_decode pins mistral decode_chunk/greedy
    # parity. Both still run in the full tier (~92s together).
    "test_torch_parity",  # torch cross-checks of the jax zoo
    "test_mistral",       # RoPE/GQA/sliding-window reference parity
    # ~75s of compile-bound distributed LM TRAINING steps — serving-
    # independent; the multi-device path keeps tier-1 smoke coverage
    # via test_multihost (fast) and full coverage via test_parallel
    # (slow). Moved with the round-14 pair above for timing margin:
    # the default tier was landing within run-to-run variance of the
    # 870s window (777s pass / ~880s miss on the same tree).
    "test_lm_train",
    # secondary encprop serving smokes (each compiles another whole
    # tiny pipeline or unet scan, ~80s together on a small host); the
    # tier-1 acceptance bars — stride-1 bit-parity on both geometries,
    # the quality-gate mechanism, key-schedule accounting, the warmed-
    # loop jit sentinel, decode-kernel parity — stay in the default
    # tier via test_encprop (round 16)
    "test_encprop_serving",
})


def pytest_collection_modifyitems(config, items):
    import os

    for item in items:
        name = os.path.basename(str(item.fspath))
        if name.endswith(".py"):
            name = name[:-3]
        if name in FAST_MODULES and \
                item.get_closest_marker("slow") is None:
            # a per-test @pytest.mark.slow inside a fast module (e.g.
            # test_distill's real-geometry compile, test_queue's two
            # real-pipeline service builds — demoted round 21 when the
            # default tier outgrew its 870s window again; round 25
            # added test_pipeline's dp-mesh smoke, test_fused_conv's
            # pipeline flag parity, and test_w8a8's generate-level
            # kill-switch/SDXL-floor confirmations for the same
            # pressure, each with its tier-1 coverage duplicated — see
            # the demoted tests' docstrings) keeps that test out of
            # the `-m fast` sweep, not just out of tier-1
            item.add_marker(pytest.mark.fast)
        if name in SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _lock_sentinel():
    """Arm the OrderedLock deadlock sentinel (utils/locks.py) in raising
    mode for EVERY test: any hierarchy/order violation a test drives
    through the converted serving locks (queue, supervisor, breakers,
    pipeline dispatch) fails that test with both acquisition sites —
    the fast tier doubles as a runtime deadlock sentinel. The observed-
    order graph resets per test so unrelated tests' acquisition orders
    can't combine into a phantom inversion."""
    from cassmantle_tpu.utils import locks

    locks.reset_observations()
    locks.enable_sentinel(raise_on_violation=True)
    yield
    locks.disable_sentinel()
    locks.reset_observations()


@pytest.fixture(autouse=True)
def _jit_sentinel():
    """Arm the jit compile-count sentinel (utils/jit_sentinel.py) for
    EVERY test, with per-test count reset — the compile-cache
    counterpart of the lock sentinel above. Arming only counts; tests
    on steady-state serving paths opt into the hard assertion with
    ``with jit_sentinel.no_new_compiles():`` after their warmup
    dispatch, so a recompile regression (a bucket key quietly becoming
    per-call) fails tier-1 instead of shipping as a latency cliff."""
    from cassmantle_tpu.utils import jit_sentinel

    jit_sentinel.reset_counts()
    jit_sentinel.enable_sentinel()
    yield
    jit_sentinel.disable_sentinel()
    jit_sentinel.reset_counts()


@pytest.fixture(autouse=True)
def _leak_sentinel():
    """Arm the thread/task/fd leak sentinel (utils/leak_sentinel.py)
    for EVERY test — the lifecycle counterpart of the two sentinels
    above. Threads still alive and tasks still pending after teardown
    fail the test with their creation site (Thread.start/create_task
    are wrapped to stamp origin stacks while armed). Fd accounting is
    log-only here: lazy process-lifetime caches (the mmap'd embedding
    table, a jax backend initializing mid-suite) legitimately open fds
    that are not per-test leaks; seeded-fd-leak tests opt into
    fd_policy="raise" themselves. Autouse fixtures set up before the
    test's requested fixtures and so tear down after them — the
    verify here runs AFTER the test's own fixtures have stopped their
    servers/queues, which is exactly the window where a still-alive
    thread means a real shutdown bug, not work in progress. Tracking
    state resets per test so one test's leak (already reported)
    cannot fail its neighbors."""
    from cassmantle_tpu.utils import leak_sentinel

    leak_sentinel.reset()
    leak_sentinel.enable_sentinel()
    snap = leak_sentinel.snapshot()
    try:
        yield
    finally:
        try:
            leak_sentinel.verify(snap)
        finally:
            leak_sentinel.disable_sentinel()
            leak_sentinel.reset()


@pytest.fixture(scope="session")
def cfg():
    return test_config()
