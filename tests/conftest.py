"""Test env: force JAX onto a virtual 8-device CPU mesh.

This is the multi-device-without-a-cluster strategy from SURVEY.md §4: all
collective/sharding tests exercise real XLA collectives on 8 host devices; the
real-chip path is covered by bench.py and the driver's dryrun.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402

from cassmantle_tpu.config import test_config  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (no pytest-asyncio here)."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(func(**kwargs))
        return True
    return None


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: async test (built-in runner)")


@pytest.fixture(scope="session")
def cfg():
    return test_config()
