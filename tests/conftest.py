"""Test env: force JAX onto a virtual 8-device CPU mesh.

This is the multi-device-without-a-cluster strategy from SURVEY.md §4: all
collective/sharding tests exercise real XLA collectives on 8 host devices; the
real-chip path is covered by bench.py and the driver's dryrun.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
# On few-core hosts the 8 virtual devices' programs serialize; XLA's default
# 40 s collective termination timeout then kills the process mid-rendezvous
# while straggler devices are still computing. Raise it well past the worst
# observed compile+step time.
for _f in (
    "--xla_cpu_collective_call_warn_stuck_timeout_seconds=300",
    "--xla_cpu_collective_call_terminate_timeout_seconds=3600",
):
    if _f.split("=")[0].lstrip("-") not in flags:
        flags = (flags + " " + _f).strip()
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

# The environment may pin JAX_PLATFORMS to a TPU plugin (e.g. axon); the
# config override below beats the env var and forces the 8 virtual CPU
# devices for every test.
jax.config.update("jax_platform_name", "cpu")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402

from cassmantle_tpu.config import test_config  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests with asyncio.run (no pytest-asyncio here)."""
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(func(**kwargs))
        return True
    return None


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: async test (built-in runner)")


@pytest.fixture(scope="session")
def cfg():
    return test_config()
