"""Sampler/decoder/blur/scorer op tests (CPU-JAX, tiny configs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.ops.blur import MAX_TAPS, device_blur, gaussian_taps
from cassmantle_tpu.ops.ddim import (
    DDIMSchedule,
    ddim_sample,
    initial_latents,
    make_cfg_denoiser,
)
from cassmantle_tpu.ops.scorer import EmbeddingScorer
from cassmantle_tpu.utils.tokenizers import (
    BPETokenizer,
    ByteTokenizer,
    WordPieceTokenizer,
)


# -- DDIM -------------------------------------------------------------------

def test_schedule_shapes_and_monotonicity():
    s = DDIMSchedule.create(num_steps=10)
    assert s.timesteps.shape == (10,)
    ts = np.asarray(s.timesteps)
    assert (np.diff(ts) < 0).all()  # descending
    ab = np.asarray(s.alpha_bars)
    abp = np.asarray(s.alpha_bars_prev)
    assert ((abp - ab) > 0).all()  # ᾱ increases as t decreases
    assert float(abp[-1]) == 1.0


def test_ddim_identity_denoiser_converges():
    """With ε̂ = 0 the sampler must return x/sqrt(ᾱ_T→0 chain) — i.e. the
    final latents equal x0 predictions; just sanity-check finiteness and
    shape preservation."""
    s = DDIMSchedule.create(num_steps=5)
    lat = initial_latents(jax.random.PRNGKey(0), 2, 64)
    out = ddim_sample(lambda x, t: jnp.zeros_like(x), lat, s)
    assert out.shape == lat.shape
    assert np.isfinite(np.asarray(out)).all()


def test_ddim_perfect_denoiser_recovers_clean_signal():
    """If eps-hat equals the true noise injected onto a clean latent at
    every step, DDIM must walk back to (approximately) the clean latent."""
    s = DDIMSchedule.create(num_steps=20)
    rng = jax.random.PRNGKey(1)
    clean = jnp.ones((1, 8, 8, 4)) * 0.3
    noise = jax.random.normal(rng, clean.shape)
    a_T = s.alpha_bars[0]
    x_T = jnp.sqrt(a_T) * clean + jnp.sqrt(1 - a_T) * noise

    def oracle(x, t):
        # true eps for this x given the clean image: eps = (x - sqrt(a)x0)/sqrt(1-a)
        idx = jnp.argmax(s.timesteps == t)
        a = s.alpha_bars[idx]
        return (x - jnp.sqrt(a) * clean) / jnp.sqrt(1.0 - a)

    out = ddim_sample(oracle, x_T, s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(clean), atol=5e-2)


def test_cfg_denoiser_guidance_scale_one_equals_cond(cfg):
    """At scale=1 guidance output == conditional branch output."""
    calls = {}

    def unet_apply(params, x, t, ctx):
        calls["ctx_batch"] = ctx.shape[0]
        # depend on context so cond != uncond
        return x * 0.1 + ctx.mean(axis=(1, 2))[:, None, None, None]

    ctx = jnp.ones((2, 4, 8))
    uncond = jnp.zeros((2, 4, 8))
    d = make_cfg_denoiser(unet_apply, None, ctx, uncond, 1.0)
    x = jnp.ones((2, 8, 8, 4))
    out = d(x, jnp.int32(5))
    assert calls["ctx_batch"] == 4  # single 2B call
    expected = x * 0.1 + 1.0
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=1e-6)


# -- blur -------------------------------------------------------------------

def test_gaussian_taps():
    w0 = gaussian_taps(0.0)
    assert w0.sum() == pytest.approx(1.0)
    assert w0[MAX_TAPS // 2] == 1.0
    w = gaussian_taps(5.0)
    assert w.sum() == pytest.approx(1.0, abs=1e-5)
    assert w[MAX_TAPS // 2] == w.max()
    np.testing.assert_allclose(w, w[::-1], atol=1e-7)  # symmetric


def test_device_blur_smooths():
    img = np.zeros((32, 32, 3), dtype=np.uint8)
    img[16, 16] = 255  # impulse
    out = device_blur(img, 4.0)
    assert out.shape == img.shape and out.dtype == np.uint8
    assert out[16, 16, 0] < 255          # energy spread out
    assert out[16, 12, 0] > 0            # neighbors received energy
    # zero radius = identity
    np.testing.assert_array_equal(device_blur(img, 0.0), img)


def test_device_blur_matches_pil_roughly():
    from PIL import Image, ImageFilter

    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, (48, 48, 3), dtype=np.uint8)
    ours = device_blur(img, 6.0).astype(float)
    pil = np.asarray(
        Image.fromarray(img).filter(ImageFilter.GaussianBlur(6.0))
    ).astype(float)
    assert np.abs(ours - pil).mean() < 6.0


# -- tokenizers -------------------------------------------------------------

def test_byte_tokenizer_roundtrip():
    t = ByteTokenizer()
    ids = t.encode("Hello, TPU!")
    assert t.decode(ids) == "Hello, TPU!"


def test_bpe_tokenizer_merges():
    # tiny vocab: bytes for 'l','o','w','e','r' + merges
    b2u = __import__(
        "cassmantle_tpu.utils.tokenizers", fromlist=["_bytes_to_unicode"]
    )._bytes_to_unicode()
    chars = {c: b2u[ord(c)] for c in "lower "}
    vocab = {v: i for i, v in enumerate(chars.values())}
    vocab[chars["l"] + chars["o"]] = len(vocab)
    vocab[chars["l"] + chars["o"] + chars["w"]] = len(vocab)
    vocab["<|endoftext|>"] = len(vocab)
    merges = [
        (chars["l"], chars["o"]),
        (chars["l"] + chars["o"], chars["w"]),
    ]
    t = BPETokenizer(vocab, merges, style="gpt2")
    ids = t.encode("low")
    assert len(ids) == 1  # fully merged
    assert t.decode(ids) == "low"


def test_clip_tokenizer_authentic_split():
    """Real CLIP splits punctuation off words, tokenizes every digit
    alone, and lowercases: 'On: on' must reach on</w> :</w> on</w> —
    the whole-word tokens the checkpoint's merge table expects. A
    whitespace-only split would fuse ':' into the word chunk, which can
    never merge to the whole-word token (real-weights mis-tokenization
    of every prompt containing punctuation; all serving prompts do)."""
    from cassmantle_tpu.utils.tokenizers import _bytes_to_unicode

    b2u = _bytes_to_unicode()
    vocab = {"<|startoftext|>": 0, "<|endoftext|>": 1,
             "on</w>": 2, ":</w>": 3, "2</w>": 4, "4</w>": 5,
             "o": 6, "n</w>": 7}
    merges = [("o", "n</w>")]
    t = BPETokenizer(vocab, merges, style="clip")
    assert t.encode("On: on") == [0, 2, 3, 2]
    # digits stand alone, each word-final
    assert t.encode("24") == [0, 4, 5]
    # whitespace cleanup: runs collapse before splitting
    assert t.encode("  on \n on ") == [0, 2, 2]
    assert t.decode([0, 2, 3, 2]) == "on : on"


def test_gpt2_tokenizer_preserves_newlines():
    """The real GPT-2 vocab carries whitespace symbols (Ġ space, Ċ
    newline); collapsing '\\n' to a space would corrupt any multi-line
    decode under real weights."""
    from cassmantle_tpu.utils.tokenizers import _bytes_to_unicode

    b2u = _bytes_to_unicode()
    chars = {c: b2u[ord(c)] for c in "low \n"}
    vocab = {v: i for i, v in enumerate(chars.values())}
    vocab["<|endoftext|>"] = len(vocab)
    t = BPETokenizer(vocab, [], style="gpt2")
    ids = t.encode("low\nlow")
    assert vocab[chars["\n"]] in ids
    assert t.decode(ids) == "low\nlow"


def test_wordpiece_tokenizer():
    vocab = {tok: i for i, tok in enumerate(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "light", "##house", "sea"]
    )}
    t = WordPieceTokenizer(vocab)
    ids = t.encode("lighthouse sea")
    assert ids[0] == vocab["[CLS]"] and ids[-1] == vocab["[SEP]"]
    assert vocab["light"] in ids and vocab["##house"] in ids
    assert t.decode(ids) == "lighthouse sea"
    assert t.encode("xyzzy")[1] == vocab["[UNK]"]


# -- scorer -----------------------------------------------------------------

@pytest.fixture(scope="module")
def scorer(cfg):
    return EmbeddingScorer(cfg.models.minilm, seq_len=8,
                           batch_buckets=(4, 16))


def test_scorer_embed_shapes(scorer, cfg):
    emb = scorer.embed(["storm", "lighthouse", "calm"])
    assert emb.shape == (3, cfg.models.minilm.hidden_size)
    np.testing.assert_allclose(np.linalg.norm(emb, axis=1), 1.0, atol=1e-4)


def test_scorer_similarity_identity(scorer):
    sims = scorer.similarity([("storm", "storm"), ("storm", "harbor")])
    assert sims[0] == pytest.approx(1.0, abs=1e-4)
    assert sims[1] < 1.0


def test_scorer_batch_padding_consistency(scorer):
    """Same text embedded alone or in a padded batch must match.
    The embed cache is cleared between the calls so the second one
    really recomputes on device (a hit would compare a row to itself)."""
    solo = scorer.embed(["glacier"])
    scorer._embed_cache.clear()
    batch = scorer.embed(["glacier", "a", "b", "c", "d"])
    np.testing.assert_allclose(solo[0], batch[0], atol=1e-4)


def test_scorer_empty(scorer):
    assert scorer.similarity([]).shape == (0,)


def test_scorer_encode_steady_state_zero_recompiles(scorer):
    """The jit compile-count sentinel pinned on the scorer encode
    path: after one warmup dispatch per batch bucket, fresh guess
    traffic in the same buckets (cache cleared, so rows really reach
    the device) compiles nothing — the /compute_score hot path cannot
    silently regress into per-request recompiles."""
    from cassmantle_tpu.utils import jit_sentinel

    scorer._embed_cache.clear()
    scorer.embed(["warm", "the", "four"])            # bucket 4
    scorer.embed(["a", "b", "c", "d", "e", "f"])     # bucket 16
    scorer._embed_cache.clear()
    with jit_sentinel.no_new_compiles():
        scorer.embed(["fresh", "guess", "words"])
        scorer._embed_cache.clear()
        scorer.embed(["one", "two", "three", "four", "five", "six"])


def _cache_counters():
    from cassmantle_tpu.utils.logging import metrics

    snap = metrics.snapshot()["counters"]
    return (snap.get("scorer.embed_cache_hits", 0),
            snap.get("scorer.embed_cache_misses", 0))


def test_scorer_embed_cache_hits_repeated_answers(scorer):
    """The /compute_score shape: the round's answer words repeat every
    request — the second embed of the same texts must be all hits, with
    rows identical to the first (content-addressed, never invalidated)."""
    scorer._embed_cache.clear()
    texts = ["breeze", "lantern"]
    h0, m0 = _cache_counters()
    first = scorer.embed(texts)
    h1, m1 = _cache_counters()
    assert (h1 - h0, m1 - m0) == (0, 2)
    second = scorer.embed(texts)
    h2, m2 = _cache_counters()
    assert (h2 - h1, m2 - m1) == (2, 0)
    np.testing.assert_array_equal(first, second)


def test_scorer_embed_cache_dedups_within_one_batch(scorer):
    """Duplicate texts in ONE call (many guesses against one answer)
    collapse to a single device row: 1 miss, the rest hits."""
    scorer._embed_cache.clear()
    h0, m0 = _cache_counters()
    emb = scorer.embed(["dune", "dune", "dune"])
    h1, m1 = _cache_counters()
    assert (h1 - h0, m1 - m0) == (2, 1)
    np.testing.assert_array_equal(emb[0], emb[1])
    np.testing.assert_array_equal(emb[0], emb[2])


def test_scorer_embed_cache_lru_eviction(scorer):
    """Capacity is enforced oldest-first; a re-embed after eviction is
    a fresh miss whose value still matches the original embedding."""
    scorer._embed_cache.clear()
    size0 = scorer._embed_cache_size
    scorer._embed_cache_size = 2
    try:
        first = scorer.embed(["ash", "bark", "cliff"])  # evicts "ash"
        assert set(scorer._embed_cache) == {"bark", "cliff"}
        h0, m0 = _cache_counters()
        again = scorer.embed(["ash"])
        h1, m1 = _cache_counters()
        assert (h1 - h0, m1 - m0) == (0, 1)
        np.testing.assert_allclose(again[0], first[0], atol=1e-5)
    finally:
        scorer._embed_cache_size = size0
        scorer._embed_cache.clear()


def test_sentencepiece_bpe_tokenizer(tmp_path):
    """SentencePiece-BPE (Mistral vocab format): ▁ word marks, merges,
    byte fallback, tokenizer.json loading."""
    import json

    from cassmantle_tpu.utils.tokenizers import SentencePieceBPETokenizer

    W = SentencePieceBPETokenizer.WORD_MARK
    vocab = {"<unk>": 0, "<s>": 1, "</s>": 2}
    for b in range(256):
        vocab[f"<0x{b:02X}>"] = len(vocab)
    for piece in (W, "l", "o", "w", W + "l", W + "lo", W + "low", "er"):
        vocab[piece] = len(vocab)
    merges = [(W, "l"), (W + "l", "o"), (W + "lo", "w"), ("e", "r")]
    spec = {"model": {"type": "BPE", "vocab": vocab,
                      "merges": [" ".join(m) for m in merges]},
            "added_tokens": [{"content": "<s>", "id": 1}]}
    path = tmp_path / "tokenizer.json"
    path.write_text(json.dumps(spec))

    t = SentencePieceBPETokenizer.from_file(str(path))
    ids = t.encode("low low")
    assert ids[0] == t.bos_id
    assert ids[1:] == [vocab[W + "low"], vocab[W + "low"]]
    assert t.decode(ids) == "low low"
    # byte fallback: 'z' has no piece -> UTF-8 byte token, decode restores
    ids_z = t.encode("z")
    assert t.decode(ids_z) == "z"
    assert all(i != t.unk_id for i in ids_z[1:])
    # newlines survive round-trip via byte fallback (not dropped), and a
    # word after \n carries no ▁ mark
    ids_nl = t.encode("low\nlow")
    assert t.decode(ids_nl) == "low\nlow"
    assert vocab["<0x0A>"] in ids_nl
    assert ids_nl[1:] == [vocab[W + "low"], vocab["<0x0A>"],
                          vocab["l"], vocab["o"], vocab["w"]]


def test_scorer_most_similar(scorer):
    """Parity surface for the reference's word2vec most_similar
    (backend.py:297-301): exact word ranks first, top_k bounds output."""
    cands = ["storm", "stormy", "calm", "glass"]
    out = scorer.most_similar("stormy", cands, top_k=2)
    assert len(out) == 2
    words = [w for w, _ in out]
    assert "stormy" in words  # identical text embeds identically
    top_word, top_sim = out[0]
    assert top_word == "stormy" and top_sim == pytest.approx(1.0, abs=1e-3)
    assert scorer.most_similar("x", [], top_k=3) == []
