"""Speculative decoding (ops/decode.py::speculative_decode): the spec
path's whole correctness claim is BIT-PARITY with ``greedy_decode`` —
acceptance is exact argmax match, so draft quality may change speed but
never output. These tests pin that claim on CPU for both draft sources
(self-drafting n-gram lookup and a second zoo LM) across bucket shapes,
pin the ``decode_chunk`` multi-token forward against a sequence of
single ``decode_step`` calls for both LM families, and pin the
``greedy_decode`` edge semantics (eos at the first generated position,
no eos within budget, a prompt exactly filling its bucket) that the
spec path has to match.
"""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import (
    GPT2Config,
    MistralConfig,
    SpecDecodeConfig,
)
from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.models.gpt2 import GPT2LM
from cassmantle_tpu.models.mistral import MistralLM
from cassmantle_tpu.ops.decode import (
    ModelDraft,
    NgramDraft,
    greedy_decode,
    make_apply_fns,
    speculative_decode,
)
from cassmantle_tpu.serving.pipeline import PromptGenerator


@pytest.fixture(scope="module")
def base_cfg():
    return _tiny_config()


@pytest.fixture(scope="module")
def gpt2_lm(base_cfg):
    """(cfg, params, apply_fns) for ops-level decode tests."""
    cfg = base_cfg.models.gpt2
    model = GPT2LM(cfg)
    ids = jnp.zeros((1, 8), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    return cfg, params, make_apply_fns(model)


def _prompt(b, p, vocab, seed=3):
    """Right-padded (B, P) prompt bucket with per-row lengths."""
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, size=(b, p)).astype(np.int32)
    lens = np.linspace(max(2, p // 2), p, num=b).astype(np.int32)
    for i, n in enumerate(lens):
        ids[i, n:] = 0
    return jnp.asarray(ids), jnp.asarray(lens)


# -- decode_chunk vs decode_step: one forward == S single steps -------------


def test_decode_chunk_matches_step_sequence_gpt2(gpt2_lm):
    """decode_chunk scores S positions in one forward with logits equal
    to feeding the same tokens one decode_step at a time — the verify
    forward's contract (models/layers.py chunk_causal_mask)."""
    cfg, params, (prefill, step, chunk) = gpt2_lm
    ids, lens = _prompt(2, 8, cfg.vocab_size)
    max_len = 24
    last, cache0 = prefill(params, ids, lens, max_len)
    toks = jnp.asarray(
        np.random.RandomState(5).randint(0, cfg.vocab_size, (2, 5)),
        dtype=jnp.int32)
    positions = jnp.arange(max_len)[None, :]
    prompt_valid = positions < lens[:, None]

    stepped = []
    cache = cache0
    for j in range(5):
        idx = jnp.int32(8 + j)
        valid = prompt_valid | ((positions >= 8) & (positions <= idx))
        logits, cache = step(params, toks[:, j], idx, cache, valid)
        stepped.append(logits)
    stepped = jnp.stack(stepped, axis=1)               # (B, 5, V)

    valid = prompt_valid | ((positions >= 8) & (positions <= 12))
    chunked, cache_c = chunk(params, toks, jnp.int32(8), cache0, valid)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(stepped),
                               rtol=2e-5, atol=2e-5)
    # the chunk-append lands the same kv slab the stepped path wrote
    for (ck, cv), (sk, sv) in zip(cache_c, cache):
        np.testing.assert_allclose(np.asarray(ck), np.asarray(sk),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(cv), np.asarray(sv),
                                   rtol=2e-5, atol=2e-5)


def test_decode_chunk_matches_step_sequence_mistral():
    """Same contract for the Mistral family: RoPE follows true positions
    and the sliding window is enforced PER QUERY inside the chunk (the
    prompt here is longer than the window, so early cache positions must
    drop out of later queries' bands)."""
    cfg = MistralConfig.tiny()             # sliding_window=16
    model = MistralLM(cfg)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), dtype=jnp.int32))
    prefill, step, chunk = make_apply_fns(model)
    p, s, max_len = 24, 6, 40              # 24 > window: band active
    ids, lens = _prompt(2, p, cfg.vocab_size, seed=7)
    last, cache0 = prefill(params, ids, lens, max_len)
    toks = jnp.asarray(
        np.random.RandomState(9).randint(0, cfg.vocab_size, (2, s)),
        dtype=jnp.int32)
    positions = jnp.arange(max_len)[None, :]
    prompt_valid = positions < lens[:, None]

    stepped = []
    cache = cache0
    for j in range(s):
        idx = jnp.int32(p + j)
        valid = prompt_valid | ((positions >= p) & (positions <= idx))
        logits, cache = step(params, toks[:, j], idx, cache, valid)
        stepped.append(logits)
    stepped = jnp.stack(stepped, axis=1)

    valid = prompt_valid | ((positions >= p) & (positions <= p + s - 1))
    chunked, _ = chunk(params, toks, jnp.int32(p), cache0, valid)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(stepped),
                               rtol=2e-5, atol=2e-5)


# -- greedy_decode edge semantics (the spec the spec path must match) -------


def test_greedy_eos_at_first_generated_position(gpt2_lm):
    """If the very first generated token is EOS: gen_len == 0 and every
    output position reads EOS (the eos-freeze fill)."""
    cfg, params, fns = gpt2_lm
    ids, lens = _prompt(1, 8, cfg.vocab_size)
    # run once with an unreachable eos to learn the first greedy token,
    # then make THAT token the eos — deterministic eos-at-position-0
    toks, _ = greedy_decode(fns[:2], params, ids, lens,
                            jax.random.PRNGKey(0), 6, cfg.vocab_size)
    first = int(toks[0, 0])
    toks, gen_len = greedy_decode(fns[:2], params, ids, lens,
                                  jax.random.PRNGKey(0), 6, first)
    assert int(gen_len[0]) == 0
    assert np.all(np.asarray(toks) == first)


def test_greedy_no_eos_within_budget(gpt2_lm):
    """An eos that never fires (the serving layer's out-of-vocab
    sentinel) must yield gen_len == max_new for every row."""
    cfg, params, fns = gpt2_lm
    ids, lens = _prompt(3, 8, cfg.vocab_size)
    toks, gen_len = greedy_decode(fns[:2], params, ids, lens,
                                  jax.random.PRNGKey(0), 6, cfg.vocab_size)
    assert toks.shape == (3, 6)
    assert np.all(np.asarray(gen_len) == 6)


def test_greedy_tokens_after_eos_are_eos(gpt2_lm):
    """Tokens past the first EOS are overwritten with EOS and gen_len
    stops there — the mid-sequence eos-freeze convention."""
    cfg, params, fns = gpt2_lm
    ids, lens = _prompt(1, 8, cfg.vocab_size)
    toks, _ = greedy_decode(fns[:2], params, ids, lens,
                            jax.random.PRNGKey(0), 6, cfg.vocab_size)
    row = np.asarray(toks)[0]
    mid = int(row[3])                      # make a mid-chain token the eos
    j = int(np.argmax(row == mid))         # its FIRST occurrence
    toks2, gen_len2 = greedy_decode(fns[:2], params, ids, lens,
                                    jax.random.PRNGKey(0), 6, mid)
    row2 = np.asarray(toks2)[0]
    np.testing.assert_array_equal(row2[:j], row[:j])
    assert int(gen_len2[0]) == j
    assert np.all(row2[j:] == mid)


# -- speculative_decode: bit-parity with greedy_decode ----------------------


def _spec_parity_case(gpt2_lm, draft, draft_params, b, p, max_new, eos,
                      gamma=3):
    cfg, params, fns = gpt2_lm
    ids, lens = _prompt(b, p, cfg.vocab_size)
    ref_t, ref_l = greedy_decode(fns[:2], params, ids, lens,
                                 jax.random.PRNGKey(0), max_new, eos)
    got_t, got_l, stats = speculative_decode(
        fns, params, ids, lens, max_new, eos, gamma, draft, draft_params)
    np.testing.assert_array_equal(np.asarray(got_t), np.asarray(ref_t))
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(ref_l))
    return np.asarray(ref_t), np.asarray(stats)


@pytest.mark.parametrize("shape", [(1, 16), (3, 32)])
def test_spec_parity_ngram_ops(gpt2_lm, shape):
    """n-gram draft, two (B, P) bucket shapes, eos unreachable: tokens
    and gen_len bit-identical, and chunks + accepted == max_new (every
    chunk commits 1 + accepted tokens; the loop stops exactly at the
    budget when nothing terminates early)."""
    cfg = gpt2_lm[0]
    b, p = shape
    _, stats = _spec_parity_case(gpt2_lm, NgramDraft(ngram=2), None,
                                 b, p, 8, cfg.vocab_size)
    chunks, drafted, accepted = (int(x) for x in stats)
    assert chunks >= 1 and drafted == 3 * chunks
    assert 0 <= accepted <= drafted
    assert chunks + accepted == 8


@pytest.mark.parametrize("shape", [(1, 16), (3, 32)])
def test_spec_parity_model_draft_ops(gpt2_lm, shape):
    """Self-draft ModelDraft (the degenerate where draft == target),
    same parity bar across both bucket shapes."""
    cfg, params, fns = gpt2_lm
    b, p = shape
    draft = ModelDraft(fns[0], fns[1])
    _spec_parity_case(gpt2_lm, draft, params, b, p, 8, cfg.vocab_size)


def test_spec_self_draft_full_acceptance(gpt2_lm):
    """A draft identical to the target must have every proposal
    accepted (the self-draft degenerate is an exact oracle), so 8
    tokens commit in ceil(8/(gamma+1)) verify forwards. Regression for
    the draft-cache sync step: without it, stale kv at each chunk's
    correction position (the rejected token's kv on partial accept, a
    zero-filled slot on full accept) compounded and silently eroded
    the accept rate to ~0.2 on this exact setup."""
    cfg, params, fns = gpt2_lm
    ids, lens = _prompt(1, 16, cfg.vocab_size)
    draft = ModelDraft(fns[0], fns[1])
    _, _, stats = speculative_decode(fns, params, ids, lens, 8,
                                     cfg.vocab_size, 3, draft, params)
    chunks, drafted, accepted = (int(x) for x in np.asarray(stats))
    assert accepted == drafted
    assert chunks == 2


def test_spec_parity_with_midstream_eos(gpt2_lm):
    """An eos that fires mid-generation (and at different steps per
    row) exercises the done-row lockstep masking: finished rows must
    not throttle live rows, and output stays bit-identical."""
    cfg, params, fns = gpt2_lm
    ids, lens = _prompt(3, 16, cfg.vocab_size)
    ref_t, _ = greedy_decode(fns[:2], params, ids, lens,
                             jax.random.PRNGKey(0), 8, cfg.vocab_size)
    eos = int(np.asarray(ref_t)[0, 4])     # row 0 terminates at step 4
    _spec_parity_case(gpt2_lm, NgramDraft(ngram=2), None, 3, 16, 8, eos)


def test_spec_parity_eos_at_first_position(gpt2_lm):
    """The eos-at-position-0 edge through the SPEC path: gen_len 0,
    all-eos fill, bit-identical to greedy."""
    cfg, params, fns = gpt2_lm
    ids, lens = _prompt(1, 16, cfg.vocab_size)
    ref_t, _ = greedy_decode(fns[:2], params, ids, lens,
                             jax.random.PRNGKey(0), 8, cfg.vocab_size)
    eos = int(np.asarray(ref_t)[0, 0])
    toks, stats = _spec_parity_case(gpt2_lm, NgramDraft(ngram=2), None,
                                    1, 16, 8, eos)
    assert np.all(toks == eos)


def test_spec_parity_budget_smaller_than_gamma(gpt2_lm):
    """max_new < gamma: the never-overshoot clip caps the last chunk's
    commit at the budget; output still bit-identical."""
    cfg = gpt2_lm[0]
    _, stats = _spec_parity_case(gpt2_lm, NgramDraft(ngram=2), None,
                                 1, 16, 2, cfg.vocab_size, gamma=4)
    assert int(stats[0]) <= 2              # at most one chunk per token


# -- the serving path (PromptGenerator) -------------------------------------


@pytest.fixture(scope="module")
def plain_gen(base_cfg):
    return PromptGenerator(base_cfg)


@pytest.fixture(scope="module")
def ngram_gen(base_cfg):
    return PromptGenerator(base_cfg.replace(
        spec_decode=SpecDecodeConfig(mode="ngram", gamma=3, ngram=2)))


def test_promptgen_spec_parity_and_bucket_boundary(plain_gen, ngram_gen):
    """decode_ids_batch parity through the serving layer, including a
    prompt of EXACTLY 32 byte-tokens (the _bucket_for boundary: it must
    fill bucket 32, not spill into the next), co-batched with a short
    prompt (bucket padding dummies in play)."""
    boundary = "x" * 32                    # byte tokenizer: 1 char = 1 token
    assert len(plain_gen.tokenizer.encode(boundary)) == 32
    assert plain_gen._bucket_for(32, 8, 55) == 32
    texts = [boundary, "the storm rolled"]
    ref_t, ref_l = plain_gen.decode_ids_batch(texts, max_new_tokens=8,
                                              seed=0)
    got_t, got_l = ngram_gen.decode_ids_batch(texts, max_new_tokens=8,
                                              seed=0)
    np.testing.assert_array_equal(np.asarray(got_t), np.asarray(ref_t))
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(ref_l))
    assert ngram_gen.last_spec_stats["chunks"] >= 1
    # rows also equal their own single decodes (the own-bucket contract)
    for i, t in enumerate(texts):
        one_t, one_l = plain_gen.decode_ids(t, max_new_tokens=8, seed=0)
        np.testing.assert_array_equal(np.asarray(ref_t)[i],
                                      np.asarray(one_t)[0])


def test_promptgen_spec_parity_two_buckets_both_drafts(base_cfg):
    """Acceptance bar: bit-parity for BOTH draft sources across two
    prompt-bucket shapes (32 and 64 — position table widened so the
    64 bucket keeps room for the chunk scratch tail), with the
    draft-model source using a genuinely smaller second LM (its own
    params and cache, not the self-draft degenerate)."""
    big = base_cfg.replace(models=dc.replace(
        base_cfg.models,
        gpt2=dc.replace(base_cfg.models.gpt2, max_positions=128)))
    small_draft = GPT2Config(vocab_size=256, hidden_size=32, num_layers=1,
                             num_heads=2, max_positions=128,
                             dtype="float32")
    texts = ["storm", "y" * 40]            # buckets 32 and 64
    plain = PromptGenerator(big)
    ref_t, ref_l = plain.decode_ids_batch(texts, max_new_tokens=8, seed=0)
    for spec_cfg in (
        SpecDecodeConfig(mode="ngram", gamma=4, ngram=2),
        SpecDecodeConfig(mode="draft_model", gamma=4,
                         draft_model=small_draft),
    ):
        gen = PromptGenerator(big.replace(spec_decode=spec_cfg))
        got_t, got_l = gen.decode_ids_batch(texts, max_new_tokens=8,
                                            seed=0)
        np.testing.assert_array_equal(np.asarray(got_t),
                                      np.asarray(ref_t))
        np.testing.assert_array_equal(np.asarray(got_l),
                                      np.asarray(ref_l))
        assert gen.last_spec_stats["chunks"] >= 2  # both buckets drafted


def test_promptgen_spec_parity_mistral(base_cfg):
    """The Mistral family through the serving spec path (ngram draft):
    sliding-window chunk masking must hold bit-parity end to end."""
    mcfg = base_cfg.replace(models=dc.replace(
        base_cfg.models, mistral=MistralConfig.tiny()))
    texts = ["the storm rolled over the", "b c d b c d b c d"]
    plain = PromptGenerator(mcfg)
    spec = PromptGenerator(mcfg.replace(
        spec_decode=SpecDecodeConfig(mode="ngram", gamma=3, ngram=2)))
    ref_t, ref_l = plain.decode_ids_batch(texts, max_new_tokens=8, seed=0)
    got_t, got_l = spec.decode_ids_batch(texts, max_new_tokens=8, seed=0)
    np.testing.assert_array_equal(np.asarray(got_t), np.asarray(ref_t))
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(ref_l))
    assert spec.last_spec_stats["chunks"] >= 1


def test_promptgen_generate_batch_ab_smoke(plain_gen, ngram_gen):
    """The tier-1 A/B smoke (ISSUE 5 satellite): draft + verify run end
    to end through generate_batch, output text matches the plain
    generator exactly, the accept rate is NONZERO (a repetitive prompt
    the n-gram lookup can actually predict), and the chunk count shows
    real amortization (fewer verify forwards than tokens)."""
    texts = ["b c d b c d b c d b c d", "the storm rolled"]
    ref = plain_gen.generate_batch(texts, max_new_tokens=8)
    got = ngram_gen.generate_batch(texts, max_new_tokens=8)
    assert got == ref
    stats = ngram_gen.last_spec_stats
    assert stats["accepted"] > 0
    assert stats["accept_rate"] > 0.0
    # 2 bucket groups x 8 tokens = 16 stepped forwards on the plain
    # path; accepted drafts must have saved at least one verify forward
    assert stats["chunks"] < 16
    from cassmantle_tpu.utils.logging import metrics

    snap = metrics.snapshot()
    assert snap["counters"]["decode.spec_chunks"] >= stats["chunks"]
    assert "decode.spec_accept_rate" in snap["gauges"]
    assert snap["timings"]["decode.verify_s"]["count"] >= 1


def test_promptgen_spec_reuses_compiled_buckets(ngram_gen):
    """Batches of 3 and 4 share the (4, P) spec graph — the serving
    buckets compile once (the greedy path's guarantee, kept)."""
    ngram_gen.decode_ids_batch(["a", "b", "c"], max_new_tokens=4)
    misses = speculative_decode._cache_size()
    ngram_gen.decode_ids_batch(["d", "e", "f", "g"], max_new_tokens=4)
    assert speculative_decode._cache_size() == misses


def test_promptgen_steady_state_zero_recompiles(plain_gen, ngram_gen):
    """The jit compile-count sentinel (utils/jit_sentinel.py), pinned
    on the real prompt-decode serving path: after one warmup dispatch
    per (prompt bucket, batch bucket) pair, further decode traffic in
    the SAME buckets — different texts, different seeds, both the
    greedy and the speculative path — compiles NOTHING. A bucket key
    quietly becoming per-call (the recompile-hazard class) fails here
    instead of shipping as a silent latency cliff."""
    from cassmantle_tpu.utils import jit_sentinel

    # warmup: one dispatch per (prompt 32, batch 4) and (32, 1) bucket
    plain_gen.decode_ids_batch(["a storm", "a tide", "a dune"],
                               max_new_tokens=4)
    plain_gen.decode_ids_batch(["a solo warm dispatch"],
                               max_new_tokens=4)
    ngram_gen.decode_ids_batch(["a storm", "a tide", "a dune"],
                               max_new_tokens=4)
    with jit_sentinel.no_new_compiles():
        plain_gen.decode_ids_batch(["new words", "другой", "third?"],
                                   max_new_tokens=4)
        plain_gen.decode_ids_batch(["and a fourth dispatch"],
                                   max_new_tokens=4)
        ngram_gen.decode_ids_batch(["fresh texts here", "again",
                                    "and again"], max_new_tokens=4)


def test_promptgen_seeded_recompile_fails_steady_state(ngram_gen):
    """The sentinel actually ARMS the steady-state contract: traffic
    that enters a cold batch bucket inside the assertion window (a
    seeded recompile regression) raises JitRecompileError naming the
    compiled function."""
    from cassmantle_tpu.utils import jit_sentinel

    ngram_gen.decode_ids_batch(["warm", "the", "bucket"],
                               max_new_tokens=4)
    with pytest.raises(jit_sentinel.JitRecompileError):
        with jit_sentinel.no_new_compiles():
            # 5 rows -> batch bucket 8: a bucket this module never
            # warmed, so the spec graph must compile mid-window
            ngram_gen.decode_ids_batch(
                ["a", "b", "c", "d", "e"], max_new_tokens=4)


def test_promptgen_spec_falls_back_when_bucket_lacks_scratch_room(
        ngram_gen, plain_gen):
    """A prompt whose bucket + budget + scratch tail exceeds the
    position table must silently take the plain greedy path (same
    output, no spec stats) instead of overrunning the wpe table."""
    long_text = "z" * 40                   # bucket 55 (the limit); 55+8+4>64
    assert not ngram_gen._spec_enabled(55, 8)
    before = ngram_gen.last_spec_stats
    got_t, got_l = ngram_gen.decode_ids_batch([long_text],
                                              max_new_tokens=8, seed=0)
    assert ngram_gen.last_spec_stats is before  # untouched: greedy path
    ref_t, ref_l = plain_gen.decode_ids_batch([long_text],
                                              max_new_tokens=8, seed=0)
    np.testing.assert_array_equal(np.asarray(got_t), np.asarray(ref_t))


def test_promptgen_kill_switch(base_cfg, plain_gen, monkeypatch):
    """CASSMANTLE_NO_SPEC_DECODE=1 (docs/DEPLOY.md §6) forces the plain
    greedy path even with spec_decode configured on."""
    monkeypatch.setenv("CASSMANTLE_NO_SPEC_DECODE", "1")
    gen = PromptGenerator(base_cfg.replace(
        spec_decode=SpecDecodeConfig(mode="ngram", gamma=3, ngram=2)))
    assert not gen._spec_enabled(32, 8)
    t, l = gen.decode_ids_batch(["the storm rolled"], max_new_tokens=8,
                                seed=0)
    assert gen.last_spec_stats is None
    ref_t, _ = plain_gen.decode_ids_batch(["the storm rolled"],
                                          max_new_tokens=8, seed=0)
    np.testing.assert_array_equal(np.asarray(t), np.asarray(ref_t))


def test_promptgen_temperature_disables_spec(base_cfg):
    """Sampled decodes (temperature > 0) never take the spec path —
    exact-argmax acceptance is only sound for greedy."""
    cfg = base_cfg.replace(
        sampler=dc.replace(base_cfg.sampler, text_temperature=0.8),
        spec_decode=SpecDecodeConfig(mode="ngram", gamma=3))
    gen = PromptGenerator(cfg)
    assert not gen._spec_enabled(32, 8)
