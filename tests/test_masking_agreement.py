"""Mask-selection agreement with the reference algorithm (VERDICT r3
item 7): the vendored POS classifier (engine/pos.py) must reproduce
the reference's NLTK {JJ*, RB*, NN, NNS} candidate filter closely
enough that end-to-end mask selection agrees on a gold corpus.

Reference semantics replayed exactly by eval/masking_agreement.py:
src/utils.py:81-104 (descriptive_tags filter, distance-from-mean
ranking, idf==1, first-occurrence index lookup).
"""

from cassmantle_tpu.engine.content import hash_embed
from cassmantle_tpu.engine.pos import is_maskable
from cassmantle_tpu.eval.masking_agreement import (
    GOLD_PATH,
    evaluate,
    load_gold,
)
from cassmantle_tpu.utils.text import tokenize_words


def test_agreement_thresholds():
    """Per-section bars (VERDICT r4 #6: the corpus now includes
    adversarial registers, so one overall number would either hide the
    gaps or force the bar below meaning). The PRODUCTION register —
    past-tense narrative, which the pipeline's templates and seeds
    produce, plus verbatim pipeline output strings — keeps the strict
    round-3 bar; adversarial sections get regression floors at their
    measured level so a classifier change that degrades them surfaces."""
    report = evaluate(hash_embed)
    assert report["prompts"] >= 150
    sec = report["by_section"]
    # production register: strict
    for name in ("core-past-narrative", "pipeline-outputs"):
        assert sec[name]["tag_accuracy"] >= 0.98, (name, report)
        assert sec[name]["mask_agreement"] >= 0.90, (name, report)
    assert sec["past-narrative-hard"]["tag_accuracy"] >= 0.95, report
    # adversarial registers: floors just under the measured level
    # (docs/POS_ANNOTATION.md documents the known gaps behind them)
    assert sec["adversarial-homographs"]["tag_accuracy"] >= 0.90, report
    assert sec["present-tense"]["tag_accuracy"] >= 0.84, report
    assert sec["imperatives"]["tag_accuracy"] >= 0.86, report
    # whole-corpus floors
    assert report["tag_accuracy"] >= 0.94, report
    assert report["mask_agreement"] >= 0.75, report["disagreements"][:5]
    assert report["mean_jaccard"] >= 0.82, report


def test_gold_corpus_well_formed():
    gold = load_gold(GOLD_PATH)
    assert len(gold) >= 150
    for tagged in gold:
        assert len(tagged) >= 8
        # prose prompts carry one or two annotated terminators; the
        # styled image-prompt lines (pipeline-outputs) carry none
        assert sum(1 for w, t in tagged if w == ".") <= 2


def _maskable_words(text):
    toks = tokenize_words(text)
    return [t for i, t in enumerate(toks) if is_maskable(toks, i)]


def test_verbs_excluded():
    """The round-3 weakness: verbs that survive a stopword list
    ('crossed', 'stood') must not be maskable (reference tags them
    VBD, outside descriptive_tags)."""
    words = _maskable_words(
        "The caravan crossed the dunes. A keeper stood near the gate.")
    assert "crossed" not in words and "stood" not in words
    assert "caravan" in words and "dunes" in words and "keeper" in words


def test_attributive_participles_maskable():
    words = _maskable_words(
        "A gilded caravan crossed the silver dunes under striped "
        "awnings.")
    assert "gilded" in words and "striped" in words
    assert "crossed" not in words


def test_proper_nouns_excluded():
    words = _maskable_words("The ship reached Lisbon before dawn.")
    assert "Lisbon" not in words
    assert "ship" in words and "dawn" in words


def test_ing_nouns_kept_gerunds_dropped():
    words = _maskable_words(
        "A lantern hung on the railing, humming in the morning wind.")
    assert "railing" in words and "morning" in words
    assert "humming" not in words


def test_determiner_rescues_noun_homographs():
    words = _maskable_words("She painted a rose beside the saw.")
    assert "rose" in words and "saw" in words


def test_adverbs_maskable():
    words = _maskable_words("The bell tolled softly across the valley.")
    assert "softly" in words
    assert "tolled" not in words
