"""Mask-selection agreement with the reference algorithm (VERDICT r3
item 7): the vendored POS classifier (engine/pos.py) must reproduce
the reference's NLTK {JJ*, RB*, NN, NNS} candidate filter closely
enough that end-to-end mask selection agrees on a gold corpus.

Reference semantics replayed exactly by eval/masking_agreement.py:
src/utils.py:81-104 (descriptive_tags filter, distance-from-mean
ranking, idf==1, first-occurrence index lookup).
"""

from cassmantle_tpu.engine.content import hash_embed
from cassmantle_tpu.engine.pos import is_maskable
from cassmantle_tpu.eval.masking_agreement import (
    GOLD_PATH,
    evaluate,
    load_gold,
)
from cassmantle_tpu.utils.text import tokenize_words


def test_agreement_thresholds():
    """VERDICT bar: >=80% selection agreement. The classifier sits
    well above it; the assertions pin a margin so regressions surface
    before parity decays to the bar."""
    report = evaluate(hash_embed)
    assert report["prompts"] >= 50
    assert report["tag_accuracy"] >= 0.97, report
    assert report["mask_agreement"] >= 0.90, report["disagreements"][:5]
    assert report["mean_jaccard"] >= 0.93, report


def test_gold_corpus_well_formed():
    gold = load_gold(GOLD_PATH)
    assert len(gold) >= 50
    for tagged in gold:
        assert len(tagged) >= 8
        # two sentences per prompt, annotated terminators
        assert sum(1 for w, t in tagged if w == ".") == 2


def _maskable_words(text):
    toks = tokenize_words(text)
    return [t for i, t in enumerate(toks) if is_maskable(toks, i)]


def test_verbs_excluded():
    """The round-3 weakness: verbs that survive a stopword list
    ('crossed', 'stood') must not be maskable (reference tags them
    VBD, outside descriptive_tags)."""
    words = _maskable_words(
        "The caravan crossed the dunes. A keeper stood near the gate.")
    assert "crossed" not in words and "stood" not in words
    assert "caravan" in words and "dunes" in words and "keeper" in words


def test_attributive_participles_maskable():
    words = _maskable_words(
        "A gilded caravan crossed the silver dunes under striped "
        "awnings.")
    assert "gilded" in words and "striped" in words
    assert "crossed" not in words


def test_proper_nouns_excluded():
    words = _maskable_words("The ship reached Lisbon before dawn.")
    assert "Lisbon" not in words
    assert "ship" in words and "dawn" in words


def test_ing_nouns_kept_gerunds_dropped():
    words = _maskable_words(
        "A lantern hung on the railing, humming in the morning wind.")
    assert "railing" in words and "morning" in words
    assert "humming" not in words


def test_determiner_rescues_noun_homographs():
    words = _maskable_words("She painted a rose beside the saw.")
    assert "rose" in words and "saw" in words


def test_adverbs_maskable():
    words = _maskable_words("The bell tolled softly across the valley.")
    assert "softly" in words
    assert "tolled" not in words
