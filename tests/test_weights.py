"""Weight-conversion tests: fabricate torch/diffusers-layout checkpoints for
the tiny configs, convert, and require the result to load into the Flax
models with exactly matching tree structure + shapes, plus numeric layout
checks for the dense/conv transposes."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.models import (
    ClipTextEncoder,
    GPT2LM,
    MiniLMEncoder,
    UNet,
    VAEDecoder,
)
from cassmantle_tpu.models.weights import (
    convert_clip_text,
    convert_gpt2,
    convert_minilm,
    convert_unet,
    convert_vae_decoder,
    init_params,
    tree_shapes,
)


def _fill(shape, seed):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(shape).astype(np.float32)


def _flat(tree):
    return {
        "/".join(str(k.key) for k in path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    }


def assert_same_structure(converted, reference):
    got, want = _flat(converted), _flat(reference)
    missing = set(want) - set(got)
    extra = set(got) - set(want)
    assert not missing, f"converted tree missing params: {sorted(missing)[:8]}"
    assert not extra, f"converted tree has extra params: {sorted(extra)[:8]}"
    for key in want:
        assert got[key].shape == want[key].shape, (
            f"{key}: {got[key].shape} != {want[key].shape}"
        )


# --------------------------------------------------------------------------
# Reverse mapping: flax init tree -> fabricated torch checkpoint.
# Written independently of models/weights.py so the two directions
# cross-check each other.
# --------------------------------------------------------------------------

def _torch_dense(flax_kernel):
    return np.ascontiguousarray(np.asarray(flax_kernel).T)


def _torch_conv(flax_kernel):
    return np.ascontiguousarray(
        np.transpose(np.asarray(flax_kernel), (3, 2, 0, 1))
    )


def _fabricate_fused_qkv(out, attn, src_fmt, names):
    """Split a fused qkv Dense (layers.MultiHeadAttention fused_qkv)
    back into the published per-projection tensors; src_fmt's '{}' is
    filled with each published projection name."""
    ks = np.split(np.asarray(attn["qkv"]["kernel"]), len(names), axis=1)
    bs = np.split(np.asarray(attn["qkv"]["bias"]), len(names), axis=0)
    for n, kk, bb in zip(names, ks, bs):
        out[src_fmt.format(n) + ".weight"] = _torch_dense(kk)
        out[src_fmt.format(n) + ".bias"] = bb


def fabricate_clip(params, num_layers):
    p = params["params"]
    out = {
        "text_model.embeddings.token_embedding.weight":
            np.asarray(p["token_embedding"]["embedding"]),
        "text_model.embeddings.position_embedding.weight":
            np.asarray(p["position_embedding"]),
        "text_model.final_layer_norm.weight":
            np.asarray(p["ln_final"]["scale"]),
        "text_model.final_layer_norm.bias":
            np.asarray(p["ln_final"]["bias"]),
    }
    for i in range(num_layers):
        b = p[f"block_{i}"]
        src = f"text_model.encoder.layers.{i}"
        out[f"{src}.layer_norm1.weight"] = np.asarray(b["ln1"]["scale"])
        out[f"{src}.layer_norm1.bias"] = np.asarray(b["ln1"]["bias"])
        out[f"{src}.layer_norm2.weight"] = np.asarray(b["ln2"]["scale"])
        out[f"{src}.layer_norm2.bias"] = np.asarray(b["ln2"]["bias"])
        _fabricate_fused_qkv(out, b["attn"],
                             src + ".self_attn.{}",
                             ("q_proj", "k_proj", "v_proj"))
        out[f"{src}.self_attn.out_proj.weight"] = _torch_dense(
            b["attn"]["out"]["kernel"])
        out[f"{src}.self_attn.out_proj.bias"] = np.asarray(
            b["attn"]["out"]["bias"])
        for fc in ("fc1", "fc2"):
            out[f"{src}.mlp.{fc}.weight"] = _torch_dense(
                b["mlp"][fc]["kernel"])
            out[f"{src}.mlp.{fc}.bias"] = np.asarray(b["mlp"][fc]["bias"])
    return out


def test_convert_clip(cfg):
    model = ClipTextEncoder(cfg.models.clip_text)
    ids = jnp.zeros((1, 8), dtype=jnp.int32)
    reference = init_params(model, 0, ids)
    ckpt = fabricate_clip(reference, cfg.models.clip_text.num_layers)
    converted = convert_clip_text(ckpt, cfg.models.clip_text.num_layers)
    assert_same_structure(converted, reference)
    # numeric: converted params give identical outputs to the originals
    out_a = model.apply(reference, ids)["hidden"]
    out_b = model.apply(
        jax.tree_util.tree_map(jnp.asarray, converted), ids)["hidden"]
    np.testing.assert_allclose(out_a, out_b, atol=1e-6)


def fabricate_gpt2(params, num_layers, hidden):
    p = params["params"]
    out = {
        "wte.weight": np.asarray(p["wte"]["embedding"]),
        "wpe.weight": np.asarray(p["wpe"]["embedding"]),
        "ln_f.weight": np.asarray(p["ln_f"]["scale"]),
        "ln_f.bias": np.asarray(p["ln_f"]["bias"]),
    }
    for i in range(num_layers):
        b = p[f"block_{i}"]
        src = f"h.{i}"
        out[f"{src}.ln_1.weight"] = np.asarray(b["ln1"]["scale"])
        out[f"{src}.ln_1.bias"] = np.asarray(b["ln1"]["bias"])
        out[f"{src}.ln_2.weight"] = np.asarray(b["ln2"]["scale"])
        out[f"{src}.ln_2.bias"] = np.asarray(b["ln2"]["bias"])
        # HF Conv1D: weight (in, out); fused qkv along out axis
        out[f"{src}.attn.c_attn.weight"] = np.concatenate(
            [np.asarray(b["attn"][n]["kernel"]) for n in ("q", "k", "v")],
            axis=1,
        )
        out[f"{src}.attn.c_attn.bias"] = np.concatenate(
            [np.asarray(b["attn"][n]["bias"]) for n in ("q", "k", "v")]
        )
        out[f"{src}.attn.c_proj.weight"] = np.asarray(
            b["attn"]["out"]["kernel"])
        out[f"{src}.attn.c_proj.bias"] = np.asarray(b["attn"]["out"]["bias"])
        out[f"{src}.mlp.c_fc.weight"] = np.asarray(b["mlp"]["fc1"]["kernel"])
        out[f"{src}.mlp.c_fc.bias"] = np.asarray(b["mlp"]["fc1"]["bias"])
        out[f"{src}.mlp.c_proj.weight"] = np.asarray(
            b["mlp"]["fc2"]["kernel"])
        out[f"{src}.mlp.c_proj.bias"] = np.asarray(b["mlp"]["fc2"]["bias"])
    return out


def test_convert_gpt2(cfg):
    gcfg = cfg.models.gpt2
    model = GPT2LM(gcfg)
    ids = jnp.zeros((1, 6), dtype=jnp.int32)
    reference = init_params(model, 0, ids)
    ckpt = fabricate_gpt2(reference, gcfg.num_layers, gcfg.hidden_size)
    converted = convert_gpt2(ckpt, gcfg.num_layers, gcfg.hidden_size)
    assert_same_structure(converted, reference)
    out_a = model.apply(reference, ids)
    out_b = model.apply(jax.tree_util.tree_map(jnp.asarray, converted), ids)
    np.testing.assert_allclose(out_a, out_b, atol=1e-5)


def fabricate_minilm(params, num_layers):
    p = params["params"]
    # token_type row must be zero for exact equality (it is folded into the
    # position table by the converter).
    hidden = p["position_embeddings"].shape[1]
    out = {
        "embeddings.word_embeddings.weight":
            np.asarray(p["word_embeddings"]["embedding"]),
        "embeddings.position_embeddings.weight":
            np.asarray(p["position_embeddings"]),
        "embeddings.token_type_embeddings.weight":
            np.zeros((2, hidden), dtype=np.float32),
        "embeddings.LayerNorm.weight": np.asarray(p["embed_ln"]["scale"]),
        "embeddings.LayerNorm.bias": np.asarray(p["embed_ln"]["bias"]),
    }
    for i in range(num_layers):
        b = p[f"block_{i}"]
        src = f"encoder.layer.{i}"
        _fabricate_fused_qkv(out, b["attn"],
                             src + ".attention.self.{}",
                             ("query", "key", "value"))
        out[f"{src}.attention.output.dense.weight"] = _torch_dense(
            b["attn"]["out"]["kernel"])
        out[f"{src}.attention.output.dense.bias"] = np.asarray(
            b["attn"]["out"]["bias"])
        out[f"{src}.attention.output.LayerNorm.weight"] = np.asarray(
            b["ln1"]["scale"])
        out[f"{src}.attention.output.LayerNorm.bias"] = np.asarray(
            b["ln1"]["bias"])
        out[f"{src}.intermediate.dense.weight"] = _torch_dense(
            b["mlp"]["fc1"]["kernel"])
        out[f"{src}.intermediate.dense.bias"] = np.asarray(
            b["mlp"]["fc1"]["bias"])
        out[f"{src}.output.dense.weight"] = _torch_dense(
            b["mlp"]["fc2"]["kernel"])
        out[f"{src}.output.dense.bias"] = np.asarray(b["mlp"]["fc2"]["bias"])
        out[f"{src}.output.LayerNorm.weight"] = np.asarray(
            b["ln2"]["scale"])
        out[f"{src}.output.LayerNorm.bias"] = np.asarray(b["ln2"]["bias"])
    return out


def test_convert_minilm(cfg):
    mcfg = cfg.models.minilm
    model = MiniLMEncoder(mcfg)
    ids = jnp.zeros((1, 6), dtype=jnp.int32)
    mask = jnp.ones((1, 6), dtype=jnp.int32)
    reference = init_params(model, 0, ids, mask)
    ckpt = fabricate_minilm(reference, mcfg.num_layers)
    converted = convert_minilm(ckpt, mcfg.num_layers)
    assert_same_structure(converted, reference)
    out_a = model.apply(reference, ids, mask)
    out_b = model.apply(
        jax.tree_util.tree_map(jnp.asarray, converted), ids, mask)
    np.testing.assert_allclose(out_a, out_b, atol=1e-5)


# --------------------------------------------------------------------------
# UNet / VAE: reverse-map each flax param path to its diffusers name.
# --------------------------------------------------------------------------

def _unet_reverse_name(path, levels):
    """flax path like 'down_0_res_1/conv1/kernel' -> diffusers name."""
    parts = path.split("/")
    top = parts[0]

    def resblock_leaf(rest):
        sub = {
            "norm1/norm": "norm1", "norm2/norm": "norm2",
            "conv1": "conv1", "conv2": "conv2",
            "time_proj": "time_emb_proj", "skip": "conv_shortcut",
        }["/".join(rest[:-1])]
        return sub, rest[-1]

    def attn_leaf(rest):
        joined = "/".join(rest[:-1])
        if joined == "norm/norm":
            return "norm", rest[-1]
        if joined in ("proj_in", "proj_out"):
            return joined, rest[-1]
        m = re.match(r"block_(\d+)/(\w+)(?:/(\w+))?$", joined)
        blk, module, which = m.group(1), m.group(2), m.group(3)
        if which is None:  # e.g. block_0/ln1 -> LayerNorm leaf
            ln = {"ln1": "norm1", "ln2": "norm2", "ln3": "norm3"}[module]
            return f"transformer_blocks.{blk}.{ln}", rest[-1]
        attn_name = {"self_attn": "attn1", "cross_attn": "attn2"}.get(module)
        if attn_name:
            proj = {"q": "to_q", "k": "to_k", "v": "to_v",
                    "out": "to_out.0"}[which]
            return f"transformer_blocks.{blk}.{attn_name}.{proj}", rest[-1]
        proj = {"proj": "ff.net.0.proj", "out": "ff.net.2"}[which]
        return f"transformer_blocks.{blk}.{proj}", rest[-1]

    if top == "conv_in":
        return "conv_in", parts[-1]
    if top == "conv_out":
        return "conv_out", parts[-1]
    if top == "norm_out":
        return "conv_norm_out", parts[-1]
    if top in ("time_fc1", "time_fc2"):
        n = {"time_fc1": "time_embedding.linear_1",
             "time_fc2": "time_embedding.linear_2"}[top]
        return n, parts[-1]
    m = re.match(r"down_(\d+)_res_(\d+)", top)
    if m:
        sub, leaf = resblock_leaf(parts[1:])
        return f"down_blocks.{m.group(1)}.resnets.{m.group(2)}.{sub}", leaf
    m = re.match(r"down_(\d+)_attn_(\d+)", top)
    if m:
        sub, leaf = attn_leaf(parts[1:])
        return f"down_blocks.{m.group(1)}.attentions.{m.group(2)}.{sub}", leaf
    m = re.match(r"down_(\d+)_downsample", top)
    if m:
        return f"down_blocks.{m.group(1)}.downsamplers.0.conv", parts[-1]
    m = re.match(r"mid_res_(\d+)", top)
    if m:
        sub, leaf = resblock_leaf(parts[1:])
        return f"mid_block.resnets.{m.group(1)}.{sub}", leaf
    if top == "mid_attn":
        sub, leaf = attn_leaf(parts[1:])
        return f"mid_block.attentions.0.{sub}", leaf
    m = re.match(r"up_(\d+)_res_(\d+)", top)
    if m:
        i = levels - 1 - int(m.group(1))
        sub, leaf = resblock_leaf(parts[1:])
        return f"up_blocks.{i}.resnets.{m.group(2)}.{sub}", leaf
    m = re.match(r"up_(\d+)_attn_(\d+)", top)
    if m:
        i = levels - 1 - int(m.group(1))
        sub, leaf = attn_leaf(parts[1:])
        return f"up_blocks.{i}.attentions.{m.group(2)}.{sub}", leaf
    m = re.match(r"up_(\d+)_upsample", top)
    if m:
        i = levels - 1 - int(m.group(1))
        return f"up_blocks.{i}.upsamplers.0.conv", parts[-1]
    raise KeyError(path)


_LEAF_MAP = {"kernel": "weight", "bias": "bias",
             "scale": "weight", "embedding": "weight"}


def _to_torch_value(leaf_name, arr, torch_name):
    arr = np.asarray(arr)
    if leaf_name != "kernel":
        return arr
    if arr.ndim == 4:
        return _torch_conv(arr)
    # dense kernels that correspond to 1x1 convs in diffusers SD1.5
    if any(s in torch_name for s in ("proj_in", "proj_out")):
        return np.ascontiguousarray(arr.T)[:, :, None, None]
    return _torch_dense(arr)


def fabricate_unet(params, levels):
    out = {}
    for path, leaf in _flat(params).items():
        assert path.startswith("params/")
        rel = path[len("params/"):]
        # fused qkv/kv kernels (layers.MultiHeadAttention fused_qkv)
        # fabricate back into the PUBLISHED separate to_q/to_k/to_v
        # tensors — the checkpoint format never changed, only the
        # in-memory tree; dense_fused re-concatenates at load
        fused = re.match(r"(.*)/(self_attn/qkv|cross_attn/kv)/kernel$",
                         rel)
        if fused:
            outer, which = fused.group(1), fused.group(2)
            module = which.split("/")[0]
            anchor, _ = _unet_reverse_name(
                f"{outer}/{module}/out/kernel", levels)
            base = anchor[: -len(".to_out.0")]
            names = (("to_q", "to_k", "to_v")
                     if which.endswith("qkv") else ("to_k", "to_v"))
            for n, part in zip(names,
                               np.split(np.asarray(leaf), len(names),
                                        axis=1)):
                out[f"{base}.{n}.weight"] = _torch_dense(part)
            continue
        name, leaf_name = _unet_reverse_name(rel, levels)
        out[f"{name}.{_LEAF_MAP[leaf_name]}"] = _to_torch_value(
            leaf_name, leaf, name)
    return out


def test_convert_unet(cfg):
    ucfg = cfg.models.unet
    model = UNet(ucfg)
    lat = jnp.zeros((1, 16, 16, 4), dtype=jnp.float32)
    t = jnp.zeros((1,), dtype=jnp.int32)
    ctx = jnp.zeros((1, 8, ucfg.context_dim), dtype=jnp.float32)
    reference = init_params(model, 0, lat, t, ctx)
    ckpt = fabricate_unet(reference, len(ucfg.channel_mults))
    converted = convert_unet(ckpt, ucfg)
    assert_same_structure(converted, reference)
    out_a = model.apply(reference, lat, t, ctx)
    out_b = model.apply(
        jax.tree_util.tree_map(jnp.asarray, converted), lat, t, ctx)
    np.testing.assert_allclose(out_a, out_b, atol=1e-5)


def _vae_reverse_name(path, levels):
    parts = path.split("/")
    top = parts[0]

    def resblock_leaf(rest):
        sub = {
            "norm1/norm": "norm1", "norm2/norm": "norm2",
            "conv1": "conv1", "conv2": "conv2", "skip": "conv_shortcut",
        }["/".join(rest[:-1])]
        return sub, rest[-1]

    if top == "post_quant_conv":
        return "post_quant_conv", parts[-1]
    if top == "conv_in":
        return "decoder.conv_in", parts[-1]
    if top == "conv_out":
        return "decoder.conv_out", parts[-1]
    if top == "norm_out":
        return "decoder.conv_norm_out", parts[-1]
    m = re.match(r"mid_res_(\d+)", top)
    if m:
        sub, leaf = resblock_leaf(parts[1:])
        return f"decoder.mid_block.resnets.{m.group(1)}.{sub}", leaf
    if top == "mid_attn":
        joined = "/".join(parts[1:-1])
        if joined == "norm/norm":
            return "decoder.mid_block.attentions.0.group_norm", parts[-1]
        which = parts[2]
        proj = {"q": "to_q", "k": "to_k", "v": "to_v",
                "out": "to_out.0"}[which]
        return f"decoder.mid_block.attentions.0.{proj}", parts[-1]
    m = re.match(r"up_(\d+)_res_(\d+)", top)
    if m:
        i = levels - 1 - int(m.group(1))
        sub, leaf = resblock_leaf(parts[1:])
        return f"decoder.up_blocks.{i}.resnets.{m.group(2)}.{sub}", leaf
    m = re.match(r"up_(\d+)_upsample", top)
    if m:
        i = levels - 1 - int(m.group(1))
        return f"decoder.up_blocks.{i}.upsamplers.0.conv", parts[-1]
    raise KeyError(path)


def fabricate_vae_decoder(params, levels):
    out = {}
    for path, leaf in _flat(params).items():
        rel = path[len("params/"):]
        name, leaf_name = _vae_reverse_name(rel, levels)
        arr = np.asarray(leaf)
        if leaf_name == "kernel":
            arr = _torch_conv(arr) if arr.ndim == 4 else _torch_dense(arr)
        out[f"{name}.{_LEAF_MAP[leaf_name]}"] = arr
    return out


def test_convert_vae_decoder(cfg):
    vcfg = cfg.models.vae
    model = VAEDecoder(vcfg)
    lat = jnp.zeros((1, 8, 8, 4), dtype=jnp.float32)
    reference = init_params(model, 0, lat)
    ckpt = fabricate_vae_decoder(reference, len(vcfg.channel_mults))
    converted = convert_vae_decoder(ckpt, vcfg)
    assert_same_structure(converted, reference)
    out_a = model.apply(reference, lat)
    out_b = model.apply(jax.tree_util.tree_map(jnp.asarray, converted), lat)
    np.testing.assert_allclose(out_a, out_b, atol=1e-5)


def fabricate_clip_vision(params, num_layers):
    p = params["params"]
    out = {
        "vision_model.embeddings.class_embedding":
            np.asarray(p["class_embedding"]),
        "vision_model.embeddings.position_embedding.weight":
            np.asarray(p["position_embedding"]),
        "vision_model.embeddings.patch_embedding.weight":
            _torch_conv(p["patch_embed"]["kernel"]),
        "vision_model.pre_layrnorm.weight":  # transformers' typo'd name
            np.asarray(p["pre_ln"]["scale"]),
        "vision_model.pre_layrnorm.bias": np.asarray(p["pre_ln"]["bias"]),
        "vision_model.post_layernorm.weight":
            np.asarray(p["post_ln"]["scale"]),
        "vision_model.post_layernorm.bias":
            np.asarray(p["post_ln"]["bias"]),
        "visual_projection.weight": _torch_dense(p["projection"]),
    }
    for i in range(num_layers):
        b = p[f"block_{i}"]
        src = f"vision_model.encoder.layers.{i}"
        out[f"{src}.layer_norm1.weight"] = np.asarray(b["ln1"]["scale"])
        out[f"{src}.layer_norm1.bias"] = np.asarray(b["ln1"]["bias"])
        out[f"{src}.layer_norm2.weight"] = np.asarray(b["ln2"]["scale"])
        out[f"{src}.layer_norm2.bias"] = np.asarray(b["ln2"]["bias"])
        _fabricate_fused_qkv(out, b["attn"],
                             src + ".self_attn.{}",
                             ("q_proj", "k_proj", "v_proj"))
        out[f"{src}.self_attn.out_proj.weight"] = _torch_dense(
            b["attn"]["out"]["kernel"])
        out[f"{src}.self_attn.out_proj.bias"] = np.asarray(
            b["attn"]["out"]["bias"])
        for fc in ("fc1", "fc2"):
            out[f"{src}.mlp.{fc}.weight"] = _torch_dense(
                b["mlp"][fc]["kernel"])
            out[f"{src}.mlp.{fc}.bias"] = np.asarray(b["mlp"][fc]["bias"])
    return out


def test_convert_clip_vision():
    from cassmantle_tpu.models.clip_vision import (
        ClipVisionConfig,
        ClipVisionEncoder,
    )
    from cassmantle_tpu.models.weights import (
        convert_clip_text_projection,
        convert_clip_vision,
    )

    vcfg = ClipVisionConfig.tiny()
    model = ClipVisionEncoder(vcfg)
    img = jnp.zeros((1, vcfg.image_size, vcfg.image_size, 3))
    reference = init_params(model, 0, img)
    ckpt = fabricate_clip_vision(reference, vcfg.num_layers)
    # conversion also tolerates the corrected layer name
    ckpt["text_projection.weight"] = _fill((vcfg.projection_dim, 48), 3)
    converted = convert_clip_vision(
        {k: v for k, v in ckpt.items() if k != "text_projection.weight"},
        vcfg.num_layers,
    )
    assert_same_structure(converted, reference)
    x = jax.random.normal(jax.random.PRNGKey(1), img.shape)
    out_a = model.apply(reference, x)
    out_b = model.apply(
        jax.tree_util.tree_map(jnp.asarray, converted), x)
    np.testing.assert_allclose(out_a, out_b, atol=1e-6)
    # text projection: torch (out, in) -> ours (in, out)
    proj = convert_clip_text_projection(ckpt)
    assert proj.shape == (48, vcfg.projection_dim)


def test_convert_clip_vision_accepts_corrected_pre_ln_name():
    from cassmantle_tpu.models.clip_vision import (
        ClipVisionConfig,
        ClipVisionEncoder,
    )
    from cassmantle_tpu.models.weights import convert_clip_vision

    vcfg = ClipVisionConfig.tiny()
    model = ClipVisionEncoder(vcfg)
    img = jnp.zeros((1, vcfg.image_size, vcfg.image_size, 3))
    reference = init_params(model, 0, img)
    ckpt = fabricate_clip_vision(reference, vcfg.num_layers)
    ckpt["vision_model.pre_layernorm.weight"] = ckpt.pop(
        "vision_model.pre_layrnorm.weight")
    ckpt["vision_model.pre_layernorm.bias"] = ckpt.pop(
        "vision_model.pre_layrnorm.bias")
    converted = convert_clip_vision(ckpt, vcfg.num_layers)
    assert_same_structure(converted, reference)
