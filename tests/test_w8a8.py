"""W8A8 quantized serving (ISSUE 20): the invariants that make int8
weights+activations trustworthy in production.

1. the Pallas int8 kernels (matmul, conv3x3) are integer-exact against
   their pure-lax references — int32 accumulation with the fp32
   epilogue in the same order — including MXU tile padding on
   non-aligned shapes and the per-output-channel scale epilogue;
2. QDense is a bit-identical param-twin of nn.Dense on fp leaves: one
   checkpoint layout, and the foundation of the kill switch's
   bit-exact revert;
3. the calibration pass is deterministic and the committed artifact
   (data/act_scales.json) is signature-gated against model-config and
   calibration-set drift — tier-1 fails fast with the rebuild command;
4. CASSMANTLE_NO_W8A8=1 reverts serving bit-exactly (never quantizes a
   leaf, counter stays silent);
5. a warmed w8a8 bucket never recompiles (jit-sentinel pinned), and
   the quality floor holds arm-vs-arm on BOTH image pipelines;
6. the prompt LM quantizes with per-token scales and ticks the
   dispatch counter once per bucket-group decode.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import (
    test_config as _tiny_config,
    test_sdxl_config as _tiny_sdxl_config,
)
from cassmantle_tpu.ops import quant, quant_matmul
from cassmantle_tpu.parallel import calibrate


def _fp_cfg():
    """The fp arm: tiny geometry on the fused-conv tree (the w8a8
    serving contract requires fused_conv, so both arms carry it — the
    A/B isolates quantization)."""
    base = _tiny_config()
    m = base.models
    return base.replace(models=dataclasses.replace(
        m, unet=dataclasses.replace(m.unet, fused_conv=True)))


def _w8a8_cfg():
    base = _fp_cfg()
    return base.replace(models=dataclasses.replace(
        base.models, unet_w8a8=True, w8a8_min_size=0))


# -- int8 kernel vs lax reference -------------------------------------------

def _rand_q(key, shape):
    return jax.random.randint(key, shape, -127, 128, jnp.int32) \
        .astype(jnp.int8)


@pytest.mark.parametrize("m,k,n", [
    (8, 64, 128),     # aligned
    (5, 70, 33),      # every dim needs MXU tile padding
    (1, 64, 129),     # decode row + odd channel count
])
def test_int8_matmul_kernel_matches_reference(m, k, n):
    """Interpret-mode kernel vs the pure-lax reference: identical
    int32 accumulation and fp32 epilogue order, so the match is exact
    — including zero-padding up to sublane/lane tiles (zero int8 pads
    contribute zero to the dot) and the per-output-channel col_scale ×
    per-token row_scale epilogue."""
    kx, kw, kr, kc, kb = jax.random.split(jax.random.PRNGKey(m * n), 5)
    x_q = _rand_q(kx, (m, k))
    w_q = _rand_q(kw, (k, n))
    row = jax.random.uniform(kr, (m, 1), jnp.float32, 0.01, 0.2)
    col = jax.random.uniform(kc, (1, n), jnp.float32, 0.001, 0.05)
    bias = jax.random.normal(kb, (n,), jnp.float32)
    got = quant_matmul.int8_matmul(x_q, w_q, row, col, bias,
                                   interpret=True)
    want = quant_matmul.int8_matmul_reference(
        x_q, w_q, row, col, bias.reshape(1, n))
    assert got.shape == (m, n)
    # int32 accumulation is exact regardless of blocking; the fp32
    # epilogue is the only rounding freedom → near-bitwise
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-5)


def test_int8_conv3x3_kernel_matches_reference():
    """Whole-image int8 conv vs the nine-shifted-dots lax reference
    (SAME padding, int32 accumulation, per-channel epilogue)."""
    kx, kw, kc, kb = jax.random.split(jax.random.PRNGKey(7), 4)
    x_q = _rand_q(kx, (2, 8, 8, 16))
    kern = _rand_q(kw, (3, 3, 16, 32))
    col = jax.random.uniform(kc, (32,), jnp.float32, 0.001, 0.05)
    bias = jax.random.normal(kb, (32,), jnp.float32)
    assert quant_matmul.int8_conv_ok(x_q, kern)
    got = quant_matmul.int8_conv3x3(x_q, kern, col, bias,
                                    interpret=True)
    want = quant_matmul.int8_conv3x3_reference(
        x_q, kern, col.reshape(1, 32), bias.reshape(1, 32))
    assert got.shape == (2, 8, 8, 32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-5)


def test_w8a8_dense_quantization_error_is_small():
    """End-to-end dense path on a quantized leaf: int8 result tracks
    the fp matmul within quantization error, static and per-token."""
    kx, kw = jax.random.split(jax.random.PRNGKey(3))
    x = jax.random.normal(kx, (6, 64), jnp.float32)
    w = jax.random.normal(kw, (64, 48), jnp.float32) * 0.1
    ref = x @ w
    for per_token in (False, True):
        q = quant.quantize_tensor_act(w)
        got = quant_matmul.w8a8_dense(x, q, per_token=per_token,
                                      interpret=True)
        err = float(jnp.abs(got - ref).max() / jnp.abs(ref).max())
        assert err < 0.05, (per_token, err)


def test_w8a8_dense_per_token_overrides_static_scale():
    """The LM contract (models/gpt2.py act_per_token): per_token=True
    always computes dynamic row scales — a stale static act_scale on
    the leaf must not change the result."""
    kx, kw = jax.random.split(jax.random.PRNGKey(11))
    x = jax.random.normal(kx, (4, 32), jnp.float32)
    w = jax.random.normal(kw, (32, 16), jnp.float32) * 0.1
    plain = quant.quantize_tensor_act(w)
    stale = plain._replace(act_scale=jnp.float32(123.0))
    a = quant_matmul.w8a8_dense(x, plain, per_token=True,
                                interpret=True)
    b = quant_matmul.w8a8_dense(x, stale, per_token=True,
                                interpret=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gn_silu_conv_w8a8_matches_reference():
    kx, ka, kb2, kw, kb = jax.random.split(jax.random.PRNGKey(5), 5)
    x = jax.random.normal(kx, (1, 8, 8, 16), jnp.float32)
    a = jax.random.uniform(ka, (1, 16), jnp.float32, 0.5, 1.5)
    b = jax.random.normal(kb2, (1, 16), jnp.float32) * 0.1
    w = jax.random.normal(kw, (3, 3, 16, 32), jnp.float32) * 0.1
    bias = jax.random.normal(kb, (32,), jnp.float32)
    q = quant.quantize_tensor_act(w)
    got = quant_matmul.gn_silu_conv3x3_w8a8(x, a, b, q, bias,
                                            interpret=True)
    want = quant_matmul.gn_silu_conv3x3_w8a8_reference(x, a, b, q, bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_qdense_is_bit_identical_param_twin_of_nn_dense():
    """QDense declares nn.Dense's exact param names/shapes/inits and
    computes identically on fp leaves — one checkpoint layout, and the
    reason the kill switch can revert bit-exactly by simply not
    quantizing at load."""
    import flax.linen as nn

    from cassmantle_tpu.models.layers import QDense

    x = jax.random.normal(jax.random.PRNGKey(0), (3, 16), jnp.float32)
    rng = jax.random.PRNGKey(42)
    pq = QDense(features=8).init(rng, x)
    pd = nn.Dense(features=8).init(rng, x)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        pq, pd)
    np.testing.assert_array_equal(
        np.asarray(QDense(features=8).apply(pq, x)),
        np.asarray(nn.Dense(features=8).apply(pd, x)))


# -- tree transform + site keys ---------------------------------------------

def test_site_key_strips_params_collection_root():
    assert quant.site_key(("params", "down_0", "conv1", "kernel")) \
        == "down_0/conv1"
    assert quant.site_key(("down_0", "conv1", "kernel")) \
        == "down_0/conv1"


def test_w8a8_tree_host_selects_sites_and_keeps_layout():
    """The transform swaps only predicate-selected kernel leaves for
    ActQTensors; every other leaf (norms, biases, embeds) is untouched
    and the tree's key structure (checkpoint layout) is unchanged."""
    from functools import partial

    from cassmantle_tpu.models.unet import UNet
    from cassmantle_tpu.models.weights import init_params

    cfg = _w8a8_cfg().models.unet
    model = UNet(cfg)
    lat = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 8, 4))
    t = jnp.array([5], jnp.int32)
    ctx = jax.random.normal(jax.random.PRNGKey(1),
                            (1, 6, cfg.context_dim))
    params = init_params(model, 0, lat, t, ctx)
    pred = partial(quant.w8a8_default_predicate, min_size=0)
    qparams = quant.w8a8_tree_host(params, predicate=pred)
    sites = quant.w8a8_site_count(qparams)
    assert sites > 0
    assert quant.w8a8_site_count(params) == 0

    def paths(tree):
        return {jax.tree_util.keystr(p)
                for p, _ in jax.tree_util.tree_flatten_with_path(
                    tree, is_leaf=lambda x: isinstance(
                        x, quant.ActQTensor))[0]}

    assert paths(params) == paths(qparams)
    # quantized trees stream fewer bytes
    assert quant.tree_nbytes(qparams) < quant.tree_nbytes(params)
    # (numerics of applying the quantized tree are covered end-to-end
    # by the pipeline quality-floor tests below — an eager apply here
    # would route every site through interpret-mode Pallas, ~20s of
    # tier-1 budget for no extra coverage)


# -- calibration + committed artifact ---------------------------------------

@pytest.mark.slow
def test_calibration_pass_is_deterministic():
    """Same (config, prompts, timesteps) → identical absmax maps: the
    latents come from fixed PRNG keys and the recorder keeps a running
    max, so --emit is reproducible."""
    cfg = calibrate.calibration_config()
    prompts = calibrate.calibration_prompts(2)
    a = calibrate.collect_unet_stats(cfg, prompts=prompts,
                                     timesteps=(981, 21))
    b = calibrate.collect_unet_stats(cfg, prompts=prompts,
                                     timesteps=(981, 21))
    assert a and a.keys() == b.keys()
    for k in a:
        assert float(a[k]) == float(b[k]), k


def test_committed_artifact_drift_gate():
    """Tier-1 drift gate: the committed data/act_scales.json signature
    must match what --emit would stamp for the current calibration
    config + calibration prompt set."""
    with open(calibrate.ACT_SCALES_PATH) as f:
        artifact = json.load(f)
    entry = artifact["entries"]["unet"]
    expect = calibrate.calibration_signature(
        calibrate.calibration_config().models,
        calibrate.prompts_digest(calibrate.calibration_prompts()))
    assert entry["signature"] == expect, (
        f"data/act_scales.json signature {entry['signature']} != "
        f"expected {expect} — the UNet/CLIP config or the calibration "
        f"seed set changed; rebuild with `python -m "
        f"cassmantle_tpu.parallel.calibrate --emit` and commit the "
        f"artifact")
    # the entry's own bookkeeping must agree with its inputs
    assert entry["prompts_digest"] == calibrate.prompts_digest(
        calibrate.calibration_prompts(entry["num_prompts"]))
    scales = entry["scales"]
    assert scales, "empty calibration entry"
    assert all(np.isfinite(v) and v > 0 for v in scales.values())


def test_load_act_scales_signature_gated():
    """Serving loads static scales ONLY for a signature-matching
    config; a drifted config falls back to dynamic (None), never
    raises."""
    m = calibrate.calibration_config().models
    scales = calibrate.load_act_scales(m)
    assert scales and all(isinstance(v, float)
                          for v in scales.values())
    drifted = dataclasses.replace(
        m, unet=dataclasses.replace(m.unet, base_channels=48))
    assert calibrate.load_act_scales(drifted) is None
    assert calibrate.load_act_scales(m, path="/nonexistent.json") is None


# -- serving: pipelines, kill switch, counters, recompiles ------------------

@pytest.fixture(scope="module")
def fp_pipe():
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    return Text2ImagePipeline(_fp_cfg())


@pytest.fixture(scope="module")
def w8a8_pipe(fp_pipe):
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    return Text2ImagePipeline(_w8a8_cfg(), share_params_with=fp_pipe)


@pytest.fixture(scope="module")
def clip_harness():
    """One tiny CLIP harness shared by both pipelines' floor tests
    (its vision-tower jits dominate the report cost)."""
    from cassmantle_tpu.eval.clip_parity import ClipSimilarityHarness
    from cassmantle_tpu.models.clip_vision import ClipVisionConfig

    return ClipSimilarityHarness(
        text_cfg=_tiny_config().models.clip_text,
        vision_cfg=ClipVisionConfig(
            image_size=32, patch_size=8, hidden_size=64,
            intermediate_size=128, num_layers=2, num_heads=4,
            projection_dim=64),
        pad_len=16)


def test_w8a8_pipeline_quantizes_counts_and_passes_floor(
        fp_pipe, w8a8_pipe, clip_harness):
    """The armed w8a8 pipeline: quantized sites with STATIC calibrated
    scales (the committed artifact matches the tiny config), the
    dispatch counter ticks steps × images, and the arm-vs-arm quality
    report clears the 0.98 floor."""
    from cassmantle_tpu.eval.clip_parity import (
        W8A8_IMAGE_SIM_FLOOR,
        w8a8_quality_report,
    )
    from cassmantle_tpu.utils.logging import metrics

    assert quant.w8a8_site_count(w8a8_pipe.unet_params) > 0
    assert quant.w8a8_calibrated(w8a8_pipe.unet_params)
    # the donor fp tree is untouched by the share
    assert quant.w8a8_site_count(fp_pipe.unet_params) == 0

    prompts = ["a lighthouse over a stormy sea"]
    before = metrics.counter_total("pipeline.w8a8_dispatches")
    fp_imgs = fp_pipe.generate(prompts, seed=3)
    assert metrics.counter_total("pipeline.w8a8_dispatches") == before
    q_imgs = w8a8_pipe.generate(prompts, seed=3)
    steps = _w8a8_cfg().sampler.num_steps
    assert metrics.counter_total("pipeline.w8a8_dispatches") \
        == before + steps * len(prompts)

    report = w8a8_quality_report(clip_harness, q_imgs, fp_imgs,
                                 prompts)
    assert report["floor"] == W8A8_IMAGE_SIM_FLOOR == 0.98
    assert report["image_sim_min"] >= report["floor"]
    assert report["passes_floor"] is True
    assert report["gate_enforced"] is False  # random init: advisory


def test_warmed_w8a8_bucket_never_recompiles(w8a8_pipe):
    """Jit sentinel pinned on the warmed w8a8 serving loop: the int8
    kernels are internal scan structure, so a second same-bucket
    generate must hit the jit cache with ZERO new compiles."""
    from cassmantle_tpu.utils import jit_sentinel

    w8a8_pipe.generate(["a quiet harbor at dawn"], seed=5)  # warmup
    with jit_sentinel.no_new_compiles():
        w8a8_pipe.generate(["a stormy night at sea"], seed=6)


def test_kill_switch_build_is_structurally_fp(fp_pipe, monkeypatch):
    """CASSMANTLE_NO_W8A8=1 at build time, tier-1 structural pin: zero
    leaves quantize and the killed build's UNet tree is leaf-for-leaf
    the SAME buffers as the fp pipeline's — combined with the
    QDense-twin bit-parity pin above, identical tree in → identical
    serving graph out. (The generate-level image comparison lives in
    the slow-tier test below: it compiles a third whole pipeline for a
    property the structural pin already forces.)"""
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    monkeypatch.setenv("CASSMANTLE_NO_W8A8", "1")
    assert quant_matmul.w8a8_disabled()
    killed = Text2ImagePipeline(_w8a8_cfg(), share_params_with=fp_pipe)
    assert quant.w8a8_site_count(killed.unet_params) == 0
    ref_leaves = jax.tree_util.tree_leaves(fp_pipe.unet_params)
    got_leaves = jax.tree_util.tree_leaves(killed.unet_params)
    assert len(ref_leaves) == len(got_leaves)
    for a, b in zip(ref_leaves, got_leaves):
        assert a is b  # shared buffers, not copies


@pytest.mark.slow
def test_kill_switch_reverts_bit_exactly(fp_pipe, monkeypatch):
    """CASSMANTLE_NO_W8A8=1 end-to-end: the killed w8a8 build's images
    are BIT-identical to the fp pipeline's and the dispatch counter
    stays silent (generate-level confirmation of the structural tier-1
    pin; slow tier — it compiles a third full pipeline)."""
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline
    from cassmantle_tpu.utils.logging import metrics

    monkeypatch.setenv("CASSMANTLE_NO_W8A8", "1")
    killed = Text2ImagePipeline(_w8a8_cfg(), share_params_with=fp_pipe)
    prompts = ["an orchard under two moons"]
    before = metrics.counter_total("pipeline.w8a8_dispatches")
    ref = fp_pipe.generate(prompts, seed=9)
    got = killed.generate(prompts, seed=9)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert metrics.counter_total("pipeline.w8a8_dispatches") == before


@pytest.mark.slow
def test_sdxl_w8a8_quantizes_and_passes_floor(clip_harness):
    """SDXL twin: two pipelines (the donor contract requires matching
    quantization mode), dynamic activation scales (the committed
    artifact is SD1.5-signature only), same 0.98 floor. Slow tier like
    the rest of the SDXL pipeline suite (test_sdxl): it compiles two
    dual-tower pipelines; the tier-1 floor runs on the SD1.5 twin
    above and the SDXL cpu_smoke receipt rides BENCH_SUITE.json."""
    from cassmantle_tpu.eval.clip_parity import w8a8_quality_report
    from cassmantle_tpu.serving.sdxl import SDXLPipeline

    base = _tiny_sdxl_config()
    m = base.models
    fp_cfg = base.replace(models=dataclasses.replace(
        m, unet=dataclasses.replace(m.unet, fused_conv=True)))
    q_cfg = fp_cfg.replace(models=dataclasses.replace(
        fp_cfg.models, unet_w8a8=True, w8a8_min_size=0))

    fp = SDXLPipeline(fp_cfg)
    with pytest.raises(AssertionError, match="quantization mode"):
        SDXLPipeline(q_cfg, share_params_with=fp)
    qp = SDXLPipeline(q_cfg)
    assert quant.w8a8_site_count(qp.unet_params) > 0
    assert not quant.w8a8_calibrated(qp.unet_params)

    prompts = ["a caravan crossing silver dunes"]
    report = w8a8_quality_report(
        clip_harness, qp.generate(prompts, seed=2),
        fp.generate(prompts, seed=2), prompts)
    assert report["passes_floor"] is True


def test_lm_w8a8_decode_counter_and_kill_switch(monkeypatch):
    """The prompt LM: lm_w8a8 quantizes the block projections
    (per-token scales, no artifact), the counter ticks once per
    bucket-group decode dispatch, and the kill switch reverts to
    bit-identical tokens with a silent counter."""
    from cassmantle_tpu.serving.pipeline import PromptGenerator
    from cassmantle_tpu.utils.logging import metrics

    base = _tiny_config()
    q_cfg = base.replace(models=dataclasses.replace(
        base.models, lm_w8a8=True, w8a8_min_size=0))

    fp = PromptGenerator(base)
    tok_fp, len_fp = fp.decode_ids_batch(["the storm rolled"],
                                         max_new_tokens=4)

    qgen = PromptGenerator(q_cfg)
    assert quant.w8a8_site_count(qgen.params) > 0
    before = metrics.counter_total("pipeline.w8a8_dispatches")
    tok_q, _ = qgen.decode_ids_batch(["the storm rolled"],
                                     max_new_tokens=4)
    assert metrics.counter_total("pipeline.w8a8_dispatches") \
        == before + 1  # one bucket group, one int8 dispatch
    assert tok_q.shape == tok_fp.shape

    monkeypatch.setenv("CASSMANTLE_NO_W8A8", "1")
    killed = PromptGenerator(q_cfg)
    assert quant.w8a8_site_count(killed.params) == 0
    before = metrics.counter_total("pipeline.w8a8_dispatches")
    tok_k, len_k = killed.decode_ids_batch(["the storm rolled"],
                                           max_new_tokens=4)
    assert metrics.counter_total("pipeline.w8a8_dispatches") == before
    np.testing.assert_array_equal(np.asarray(tok_k),
                                  np.asarray(tok_fp))
    np.testing.assert_array_equal(np.asarray(len_k),
                                  np.asarray(len_fp))


def test_w8a8_and_int8_are_mutually_exclusive():
    from cassmantle_tpu.serving.pipeline import w8a8_unet_tools

    cfg = _w8a8_cfg()
    both = dataclasses.replace(cfg.models, unet_int8=True)
    with pytest.raises(AssertionError, match="mutually exclusive"):
        w8a8_unet_tools(both)
