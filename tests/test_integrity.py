"""Output-integrity sentinels + device-loss recovery (ISSUE 17).

Covers the three rungs end to end without hardware: the device-side /
host-side detectors and their kill-switch bit-exactness (serving/
integrity.py), per-member failure through the scorer and prompt paths,
device-loss classification + the single-flight rebuild manager
(serving/device_recovery.py), the queue's device-lost fail-fast, the
checkpoint fingerprint sidecars (utils/checkpoint.py), the retry token
bucket (utils/retry.py), and the device_loss_drill harness itself.
"""

import os

import numpy as np
import pytest

from cassmantle_tpu.chaos import FAULT_POINTS, configure, disarm, parse_spec
from cassmantle_tpu.serving import integrity
from cassmantle_tpu.serving.integrity import (
    OutputInvalid,
    degenerate_frames,
    finite_verdict,
    invalid_members,
    poison,
)
from cassmantle_tpu.serving.device_recovery import (
    DeviceRecoveryManager,
    classify_device_loss,
)
from cassmantle_tpu.utils.retry import RetryBudget, retry_async


@pytest.fixture(autouse=True)
def _no_leftover_chaos():
    yield
    disarm()


# -- detectors ---------------------------------------------------------------

def test_finite_verdict_per_member():
    import jax.numpy as jnp

    x = jnp.asarray(np.array(
        [[1.0, 2.0], [np.nan, 1.0], [3.0, np.inf], [0.0, 0.0]],
        dtype=np.float32))
    assert np.asarray(finite_verdict(x)).tolist() == [
        True, False, False, True]


def test_finite_verdict_ints_constant_true():
    import jax.numpy as jnp

    toks = jnp.asarray(np.array([[1, 2], [3, 4]], dtype=np.int32))
    assert np.asarray(finite_verdict(toks)).tolist() == [True, True]


def test_degenerate_frames_flags_constant_only():
    frames = np.zeros((3, 4, 4, 3), dtype=np.uint8)
    frames[1, 0, 0, 0] = 7          # one differing pixel: a real image
    frames[2, :] = 255              # stuck-constant white
    assert degenerate_frames(frames).tolist() == [True, False, True]
    assert degenerate_frames(
        np.zeros((0, 4, 4, 3), dtype=np.uint8)).tolist() == []


def test_invalid_members_union_and_trim():
    verdict = np.array([True, False, True, True])
    frames = np.zeros((4, 2, 2, 3), dtype=np.uint8)
    frames[0, 0, 0, 0] = 9           # valid frame
    frames[2, :] = 0                 # degenerate, verdict True
    # n=3 trims the pad row before judging
    assert invalid_members(verdict, images=frames,
                           n=3).tolist() == [1, 2]


def test_invalid_members_kill_switch(monkeypatch):
    monkeypatch.setenv("CASSMANTLE_NO_INTEGRITY_CHECKS", "1")
    verdict = np.array([False, False])
    assert invalid_members(verdict).size == 0


def test_enforce_raises_retriable():
    with pytest.raises(OutputInvalid) as exc:
        integrity.enforce(np.array([True, False]), pipeline="t2i",
                          stage="sample")
    assert exc.value.retriable
    assert exc.value.members == (1,)
    assert "t2i/sample" in str(exc.value)


# -- the device.poison chaos hook --------------------------------------------

def test_poison_disarmed_is_identity():
    arr = np.ones((2, 3), dtype=np.float32)
    assert poison(arr, peer="x") is arr


def test_poison_fills_by_dtype():
    configure("seed=1;device.poison=raise:peer=x")
    f = poison(np.ones((2, 3), dtype=np.float32), peer="x")
    assert np.isnan(f[0]).all() and np.isfinite(f[1]).all()
    # signed ints get -1 (out of any vocab) so range checks catch it
    t = poison(np.ones((2, 4), dtype=np.int32), peer="x")
    assert (t[0] == -1).all() and (t[1] == 1).all()
    # uint8 frames get 0 so the degenerate detector catches it
    u = poison(np.full((2, 2, 2, 3), 7, dtype=np.uint8), peer="x")
    assert (u[0] == 0).all() and (u[1] == 7).all()


def test_poison_peer_scoped():
    configure("seed=1;device.poison=raise:peer=only-this")
    arr = np.ones((2, 3), dtype=np.float32)
    assert poison(arr, peer="other") is arr


def test_fault_points_registered():
    assert "device.poison" in FAULT_POINTS
    assert "device.lost" in FAULT_POINTS
    seed, rules = parse_spec(
        "seed=7;device.poison=flake:p=0.3,peer=a;"
        "device.lost=raise:times=1")
    assert seed == 7 and len(rules) == 2


# -- device-loss classification ----------------------------------------------

def test_classify_matches_type_names_and_markers():
    class XlaRuntimeError(Exception):
        pass

    assert classify_device_loss(XlaRuntimeError("boom")) is not None
    assert classify_device_loss(
        RuntimeError("TPU driver: data transfer failed")) is not None
    assert classify_device_loss(
        RuntimeError("chaos: injected failure at device.lost")) \
        is not None


def test_classify_walks_cause_chain():
    class XlaRuntimeError(Exception):
        pass

    outer = RuntimeError("dispatch failed")
    outer.__cause__ = XlaRuntimeError("device is lost")
    assert classify_device_loss(outer) is not None
    # cycle-safe
    a = RuntimeError("a")
    b = RuntimeError("b")
    a.__cause__, b.__cause__ = b, a
    assert classify_device_loss(a) is None


def test_classify_conservative():
    from cassmantle_tpu.serving.queue import DeadlineExceeded, QueueFull

    for exc in (ValueError("bad shape"), DeadlineExceeded("score"),
                QueueFull("score"), OutputInvalid("t2i", "sample")):
        assert classify_device_loss(exc) is None


# -- the recovery manager ----------------------------------------------------

class _FakeSupervisor:
    def __init__(self):
        self.lost = None
        self.events = []

    def note_device_lost(self, reason):
        self.lost = reason
        self.events.append(("lost", reason))

    def note_device_recovered(self):
        self.lost = None
        self.events.append(("recovered",))

    @property
    def device_lost(self):
        return self.lost

    @property
    def degraded(self):
        return self.lost is not None


def test_recovery_rebuilds_and_recovers():
    sup = _FakeSupervisor()
    calls = {"rebuild": 0, "warm": 0}

    def rebuild():
        calls["rebuild"] += 1

    def warm():
        calls["warm"] += 1

    mgr = DeviceRecoveryManager(supervisor=sup, rebuild=rebuild,
                                warm=warm, backoff_s=0.01,
                                sleep=lambda s: None)
    assert mgr.note_dispatch_exception(
        RuntimeError("chaos: injected failure at device.lost"))
    mgr.join(timeout=5.0)
    assert sup.lost is None
    assert calls == {"rebuild": 1, "warm": 1}
    assert sup.events[0][0] == "lost" and sup.events[-1][0] == "recovered"


def test_recovery_ignores_non_loss():
    sup = _FakeSupervisor()
    mgr = DeviceRecoveryManager(supervisor=sup,
                                rebuild=lambda: None)
    assert not mgr.note_dispatch_exception(ValueError("nope"))
    assert sup.lost is None and not mgr.recovering


def test_recovery_warm_failure_fails_attempt_then_permanent():
    sup = _FakeSupervisor()
    attempts = []

    def rebuild():
        attempts.append(1)

    mgr = DeviceRecoveryManager(
        supervisor=sup, rebuild=rebuild,
        warm=lambda: (_ for _ in ()).throw(RuntimeError("still dead")),
        max_attempts=2, backoff_s=0.0, sleep=lambda s: None)
    mgr.begin_recovery("test loss")
    mgr.join(timeout=5.0)
    assert len(attempts) == 2
    assert mgr.permanent
    assert sup.lost is not None  # stays device_lost: /readyz keeps 503


def test_recovery_permanent_hook_and_no_restart():
    sup = _FakeSupervisor()
    drained = []
    mgr = DeviceRecoveryManager(
        supervisor=sup,
        rebuild=lambda: (_ for _ in ()).throw(RuntimeError("dead")),
        on_permanent=drained.append, max_attempts=1, backoff_s=0.0,
        sleep=lambda s: None)
    mgr.begin_recovery("gone")
    mgr.join(timeout=5.0)
    assert drained == ["gone"]
    # permanent loss: later classifications must NOT restart recovery
    mgr.begin_recovery("gone again")
    assert not mgr.recovering and sup.lost is not None


def test_recovery_budget_bounds_attempts():
    sup = _FakeSupervisor()
    attempts = []
    budget = RetryBudget("t", capacity=2.0, refill_per_s=0.0)
    mgr = DeviceRecoveryManager(
        supervisor=sup,
        rebuild=lambda: attempts.append(1) or (_ for _ in ()).throw(
            RuntimeError("dead")),
        max_attempts=10, backoff_s=0.0, budget=budget,
        sleep=lambda s: None)
    mgr.begin_recovery("flapping")
    mgr.join(timeout=5.0)
    assert len(attempts) == 2    # budget, not max_attempts, bounded it
    assert mgr.permanent


def test_recovery_kill_switch_stays_lost(monkeypatch):
    monkeypatch.setenv("CASSMANTLE_NO_DEVICE_RECOVERY", "1")
    sup = _FakeSupervisor()
    rebuilt = []
    mgr = DeviceRecoveryManager(supervisor=sup,
                                rebuild=lambda: rebuilt.append(1))
    mgr.begin_recovery("operator will handle it")
    mgr.join(timeout=1.0)
    assert sup.lost is not None and rebuilt == [] and not mgr.recovering


# -- queue integration -------------------------------------------------------

@pytest.mark.asyncio
async def test_queue_fails_fast_while_device_lost():
    from cassmantle_tpu.serving.queue import BatchingQueue, QueueFull

    sup = _FakeSupervisor()
    sup.note_device_lost("drill")
    q = BatchingQueue(lambda items: items, name="t_lost",
                      supervisor=sup)
    with pytest.raises(QueueFull) as exc:
        await q.submit("x", deadline_s=1.0)
    assert "device_lost" in str(exc.value)
    await q.stop()


@pytest.mark.asyncio
async def test_queue_distributes_per_member_exceptions():
    from cassmantle_tpu.serving.queue import BatchingQueue

    def handler(items):
        return [OutputInvalid("drill", "score", [i])
                if item == "bad" else f"ok:{item}"
                for i, item in enumerate(items)]

    q = BatchingQueue(handler, name="t_members", max_delay_ms=20.0)
    import asyncio

    good, bad = await asyncio.gather(
        q.submit("fine", deadline_s=2.0),
        q.submit("bad", deadline_s=2.0),
        return_exceptions=True)
    assert good == "ok:fine"
    assert isinstance(bad, OutputInvalid)
    await q.stop()


@pytest.mark.asyncio
async def test_queue_dispatch_error_hook_classifies():
    from cassmantle_tpu.serving.queue import BatchingQueue

    seen = []

    def handler(items):
        raise RuntimeError("TPU driver: hardware failure")

    q = BatchingQueue(handler, name="t_hook",
                      on_dispatch_error=seen.append)
    with pytest.raises(RuntimeError):
        await q.submit("x", deadline_s=2.0)
    assert len(seen) == 1
    assert classify_device_loss(seen[0]) is not None
    await q.stop()


# -- retry budget ------------------------------------------------------------

def test_retry_budget_drain_and_refill():
    now = [0.0]
    b = RetryBudget("t", capacity=2.0, refill_per_s=1.0,
                    clock=lambda: now[0])
    assert b.acquire() and b.acquire() and not b.acquire()
    now[0] = 1.5
    assert b.acquire() and not b.acquire()
    now[0] = 100.0
    assert b.tokens() <= 2.0  # capacity-capped


@pytest.mark.asyncio
async def test_retry_async_respects_budget():
    calls = []

    async def always_fails():
        calls.append(1)
        raise RuntimeError("nope")

    b = RetryBudget("t", capacity=1.0, refill_per_s=0.0)
    with pytest.raises(RuntimeError):
        await retry_async(always_fails, max_retries=10,
                          backoff=lambda i: 0.0, name="t", budget=b)
    # first attempt free, one retry from the budget, then it breaks
    assert len(calls) == 2


# -- checkpoint fingerprints -------------------------------------------------

def test_fingerprint_record_then_verify(tmp_path):
    from cassmantle_tpu.utils.checkpoint import (
        CheckpointCorrupt,
        read_fingerprint,
        verify_or_record,
    )

    path = tmp_path / "model.safetensors"
    path.write_bytes(b"\x00" * 4096)
    verify_or_record(str(path))           # absent sidecar: records
    assert read_fingerprint(str(path)) is not None
    verify_or_record(str(path))           # match: silent
    path.write_bytes(b"\xff" * 4096)      # corrupt in place
    with pytest.raises(CheckpointCorrupt) as exc:
        verify_or_record(str(path))
    assert str(path) in str(exc.value)
    assert exc.value.expected != exc.value.actual


def test_fingerprint_covers_size_and_tail(tmp_path):
    from cassmantle_tpu.utils.checkpoint import fingerprint_file

    a = tmp_path / "a.bin"
    b = tmp_path / "b.bin"
    a.write_bytes(b"x" * 100)
    b.write_bytes(b"x" * 101)             # same head, different size
    assert fingerprint_file(str(a)) != fingerprint_file(str(b))


# -- scorer path (one tiny real encoder, shared) -----------------------------

@pytest.fixture(scope="module")
def scorer():
    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.ops.scorer import EmbeddingScorer

    return EmbeddingScorer(test_config().models.minilm, seq_len=8,
                           batch_buckets=(4,), table=False)


def test_scorer_poisoned_rows_nan_and_never_cached(scorer):
    configure("seed=3;device.poison=raise:times=1,peer=scorer")
    out = scorer.embed(["qq-poisoned", "qq-neighbor"])
    bad = ~np.isfinite(out).all(axis=-1)
    assert bad.sum() == 1          # member 0 of the dispatch corrupted
    assert np.isfinite(out[~bad]).all()  # the neighbor row is intact
    disarm()
    # the poisoned text was never cached: a clean re-embed succeeds
    again = scorer.embed(["qq-poisoned"])
    assert np.isfinite(again).all()


def test_scorer_kill_switch_bit_exact(scorer, monkeypatch):
    rows_on, ok_on = scorer._embed_device(["storm", "harbor"])
    monkeypatch.setenv("CASSMANTLE_NO_INTEGRITY_CHECKS", "1")
    rows_off, ok_off = scorer._embed_device(["storm", "harbor"])
    # the verdict is still computed in-jit either way — identical
    # compiled graphs, so flipping the switch is a bit-exact revert
    assert np.array_equal(rows_on, rows_off)
    assert ok_on.all() and ok_off.all()


def test_scorer_reload_params_zero_recompile(scorer):
    from cassmantle_tpu.utils import jit_sentinel

    scorer.embed(["warm-reload"])          # ensure compiled
    scorer.reload_params()
    with jit_sentinel.no_new_compiles():
        out = scorer.embed(["post-reload-word"])
    assert np.isfinite(out).all()


# -- prompt path (tiny GPT-2; one shared module-scoped compile, ~3s) ---------

@pytest.fixture(scope="module")
def promptgen():
    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.pipeline import PromptGenerator

    return PromptGenerator(test_config())


def test_prompt_poison_fails_only_its_row(promptgen):
    configure("seed=5;device.poison=raise:times=1,peer=prompt")
    out = promptgen.generate_batch(["the harbor", "the lighthouse"])
    invalid = [o for o in out if isinstance(o, OutputInvalid)]
    texts = [o for o in out if isinstance(o, str)]
    assert len(invalid) == 1 and len(texts) == 1
    assert invalid[0].pipeline == "prompt"
    disarm()
    # clean decode afterwards: the poison never stuck anywhere
    clean = promptgen.generate_batch(["the harbor"])
    assert isinstance(clean[0], str)


def test_prompt_generate_raises_on_poison(promptgen):
    configure("seed=5;device.poison=raise:times=1,peer=prompt")
    with pytest.raises(OutputInvalid):
        promptgen.generate("the storm")


def test_prompt_kill_switch_serves_poisoned_tokens(promptgen,
                                                   monkeypatch):
    # with checks off the range verdict is skipped entirely — the
    # production bit-exact revert (output text may be garbage, which is
    # exactly what the switch trades for zero enforcement)
    monkeypatch.setenv("CASSMANTLE_NO_INTEGRITY_CHECKS", "1")
    configure("seed=5;device.poison=raise:times=1,peer=prompt")
    out = promptgen.generate_batch(["the harbor"])
    assert isinstance(out[0], str)


# -- the drill harness (bench.py entry, short phases) ------------------------

def test_device_loss_drill_short():
    import bench

    raw = bench.device_loss_drill_run(
        seed=42, rate=60.0, baseline_s=0.3, poison_s=0.6, kill_s=2.0,
        recovered_s=0.5, rebuild_s=0.05)
    assert raw["invalid_served"] == 0
    assert all(p["all_resolved"] for p in raw["phases"].values())
    assert raw["recovery_s"] is not None and raw["recovery_s"] < 2.0
    assert raw["device_generation"] == 1
    assert raw["phases"]["recovered"]["goodput"] >= 0.9
