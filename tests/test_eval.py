import numpy as np

from cassmantle_tpu.config import ClipTextConfig
from cassmantle_tpu.eval.clip_parity import ClipSimilarityHarness
from cassmantle_tpu.models.clip_vision import ClipVisionConfig


def _tiny_harness():
    text_cfg = ClipTextConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, max_positions=16,
    )
    return ClipSimilarityHarness(
        text_cfg=text_cfg, vision_cfg=ClipVisionConfig.tiny(), pad_len=16
    )


def test_clip_similarity_shapes_and_range():
    h = _tiny_harness()
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (3, 32, 32, 3), dtype=np.uint8)
    prompts = ["a lighthouse", "a caravan", "a comet"]
    sims = h.similarity(images, prompts)
    assert sims.shape == (3,)
    assert np.isfinite(sims).all()
    assert (np.abs(sims) <= 1.0 + 1e-5).all()


def test_clip_similarity_deterministic():
    h = _tiny_harness()
    rng = np.random.default_rng(1)
    images = rng.integers(0, 255, (2, 32, 32, 3), dtype=np.uint8)
    prompts = ["storm", "harbor"]
    np.testing.assert_allclose(
        h.similarity(images, prompts), h.similarity(images, prompts)
    )


def test_parity_report():
    h = _tiny_harness()
    rng = np.random.default_rng(2)
    images = rng.integers(0, 255, (2, 32, 32, 3), dtype=np.uint8)
    report = h.parity_report(images, ["a", "b"], baseline_mean=0.3)
    assert {"clip_sim_mean", "clip_sim_std", "n", "parity_ratio"} <= set(
        report
    )


def test_harness_loads_full_checkpoint(tmp_path):
    """With a full CLIPModel-style checkpoint (text + vision towers +
    projections in ONE file) in weights_dir, the harness loads every
    stage — the parity gate is only falsifiable when real_weights=True
    in its reports."""
    import jax.numpy as jnp
    from safetensors.numpy import save_file

    from cassmantle_tpu.eval.clip_parity import ClipSimilarityHarness
    from cassmantle_tpu.models.clip_text import ClipTextEncoder
    from cassmantle_tpu.models.clip_vision import ClipVisionEncoder
    from cassmantle_tpu.models.weights import init_params
    from tests.test_weights import (
        fabricate_clip,
        fabricate_clip_vision,
        _torch_dense,
    )

    text_cfg = ClipTextConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, max_positions=16,
    )
    vcfg = ClipVisionConfig.tiny()
    text_ref = init_params(
        ClipTextEncoder(text_cfg), 0,
        jnp.zeros((1, 8), dtype=jnp.int32))
    vis_ref = init_params(
        ClipVisionEncoder(vcfg), 1,
        jnp.zeros((1, vcfg.image_size, vcfg.image_size, 3)))
    proj = np.random.default_rng(0).standard_normal(
        (text_cfg.hidden_size, vcfg.projection_dim)).astype(np.float32)
    ckpt = {**fabricate_clip(text_ref, text_cfg.num_layers),
            **fabricate_clip_vision(vis_ref, vcfg.num_layers),
            "text_projection.weight": _torch_dense(proj)}
    save_file(ckpt, str(tmp_path / "clip_text.safetensors"))

    h = ClipSimilarityHarness(
        text_cfg=text_cfg, vision_cfg=vcfg,
        weights_dir=str(tmp_path), pad_len=16)
    assert h.loaded_real_weights
    np.testing.assert_allclose(np.asarray(h.text_projection), proj)
    report = h.parity_report(
        np.zeros((1, 32, 32, 3), dtype=np.uint8), ["x"])
    assert report["real_weights"] is True
