import numpy as np

from cassmantle_tpu.config import ClipTextConfig
from cassmantle_tpu.eval.clip_parity import ClipSimilarityHarness
from cassmantle_tpu.models.clip_vision import ClipVisionConfig


def _tiny_harness():
    text_cfg = ClipTextConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, max_positions=16,
    )
    return ClipSimilarityHarness(
        text_cfg=text_cfg, vision_cfg=ClipVisionConfig.tiny(), pad_len=16
    )


def test_clip_similarity_shapes_and_range():
    h = _tiny_harness()
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (3, 32, 32, 3), dtype=np.uint8)
    prompts = ["a lighthouse", "a caravan", "a comet"]
    sims = h.similarity(images, prompts)
    assert sims.shape == (3,)
    assert np.isfinite(sims).all()
    assert (np.abs(sims) <= 1.0 + 1e-5).all()


def test_clip_similarity_deterministic():
    h = _tiny_harness()
    rng = np.random.default_rng(1)
    images = rng.integers(0, 255, (2, 32, 32, 3), dtype=np.uint8)
    prompts = ["storm", "harbor"]
    np.testing.assert_allclose(
        h.similarity(images, prompts), h.similarity(images, prompts)
    )


def test_parity_report():
    h = _tiny_harness()
    rng = np.random.default_rng(2)
    images = rng.integers(0, 255, (2, 32, 32, 3), dtype=np.uint8)
    report = h.parity_report(images, ["a", "b"], baseline_mean=0.3)
    assert {"clip_sim_mean", "clip_sim_std", "n", "parity_ratio"} <= set(
        report
    )
