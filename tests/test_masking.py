import numpy as np

from cassmantle_tpu.engine.content import hash_embed
from cassmantle_tpu.engine.masking import (
    build_prompt_state,
    candidate_indices,
    select_masks,
)
from cassmantle_tpu.utils.text import tokenize_words


def test_candidates_exclude_stopwords_and_punct():
    tokens = tokenize_words("The ancient lighthouse glows over a dark sea.")
    cands = candidate_indices(tokens)
    words = [tokens[i] for i in cands]
    assert "The" not in words and "a" not in words and "." not in words
    assert "ancient" in words and "lighthouse" in words


def test_select_masks_count_and_sorted():
    tokens = tokenize_words(
        "A restless caravan crossed the silver canyon before dawn."
    )
    masks = select_masks(tokens, hash_embed, num_masked=2)
    assert len(masks) == 2
    assert masks == sorted(masks)
    for m in masks:
        assert tokens[m][0].isalpha()


def test_select_masks_duplicate_words_distinct_positions():
    # "crimson" appears twice; masks must never point at the same index and
    # must prefer distinct words.
    tokens = tokenize_words("crimson sky over the crimson harbor tonight")
    masks = select_masks(tokens, hash_embed, num_masked=2)
    assert len(set(masks)) == 2
    assert len({tokens[m].lower() for m in masks}) == 2


def test_select_masks_degenerate_prompt():
    tokens = tokenize_words("a of to in")
    masks = select_masks(tokens, hash_embed, num_masked=2)
    assert isinstance(masks, list)


def test_build_prompt_state():
    state = build_prompt_state(
        "The gilded automaton hummed beside the frozen orchard.",
        hash_embed,
        num_masked=2,
    )
    assert set(state) == {"tokens", "masks"}
    assert len(state["masks"]) == 2
    for m in state["masks"]:
        assert 0 <= m < len(state["tokens"])


def test_hash_embed_deterministic_unit():
    v1 = hash_embed(["storm", "storm", "calm"])
    assert np.allclose(v1[0], v1[1])
    assert not np.allclose(v1[0], v1[2])
    assert np.allclose(np.linalg.norm(v1, axis=1), 1.0, atol=1e-5)


# -- register-drift guard (VERDICT r5 weak #3) ------------------------------

def test_register_drift_detects_present_tense():
    from cassmantle_tpu.engine.pos import register_drift

    # the documented VBZ gap: 3sg -s verbs in present-tense prose
    assert register_drift(tokenize_words(
        "The light fades and the city hums below the tower."))
    assert register_drift(tokenize_words(
        "The tide is rising while the lantern flickers."))


def test_register_drift_detects_imperatives():
    from cassmantle_tpu.engine.pos import register_drift

    assert register_drift(tokenize_words(
        "Gather the fallen branches near the gate."))


def test_register_drift_accepts_past_narrative():
    from cassmantle_tpu.engine.pos import register_drift

    # the production register: past-tense story prose must NOT drift
    for text in (
        "The caravan crossed the silver dunes at dawn.",
        "A restless keeper climbed the winding stair and slept.",
        "The gilded automaton hummed beside the frozen orchard.",
        "Rain tapped against the chipped cups on the sill.",
    ):
        assert not register_drift(tokenize_words(text)), text


def test_drifted_prompt_never_masks_verbs():
    tokens = tokenize_words(
        "The light fades and the city hums below the ancient tower.")
    masks = select_masks(tokens, hash_embed, num_masked=2)
    picked = {tokens[m].lower() for m in masks}
    # with the conservative fallback, the 3sg verbs cannot be masked
    assert not picked & {"fades", "hums"}, picked
    assert len(masks) == 2


def test_drift_counter_increments():
    from cassmantle_tpu.utils.logging import metrics

    before = metrics.snapshot().get("counters", {}).get(
        "masking.register_drift", 0)
    select_masks(tokenize_words(
        "Gather the fallen branches near the gate."), hash_embed, 2)
    after = metrics.snapshot().get("counters", {}).get(
        "masking.register_drift", 0)
    assert after == before + 1
