"""Pipeline (pp) + expert (ep) parallelism tests on the virtual 8-device
CPU mesh: real ppermute rings and GSPMD expert sharding, no cluster."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import MeshConfig
from cassmantle_tpu.config import test_config as tiny_config
from cassmantle_tpu.models.gpt2 import GPT2LM
from cassmantle_tpu.models.moe import (
    MoEMLP,
    moe_sharded_apply,
    shard_moe_params,
)
from cassmantle_tpu.parallel.mesh import make_mesh
from cassmantle_tpu.parallel.pipeline import (
    pipeline_apply,
    pipelined_lm_forward,
    stack_stage_params,
)


def test_pipeline_apply_matches_sequential():
    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    S = 4
    d = 16
    ks = jax.random.split(jax.random.PRNGKey(0), S + 1)
    ws = [jax.random.normal(k, (d, d)) / np.sqrt(d) for k in ks[:S]]
    stage_params = stack_stage_params([{"w": w} for w in ws])
    x = jax.random.normal(ks[-1], (8, d))

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    out = pipeline_apply(stage_fn, stage_params, x, mesh)

    ref = x
    for w in ws:
        ref = jnp.tanh(ref @ w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_apply_more_microbatches_than_stages():
    mesh = make_mesh(MeshConfig(dp=-1, pp=2))
    d = 8
    ws = [jnp.eye(d) * 0.5, jnp.eye(d) * 2.0]
    stage_params = stack_stage_params([{"w": w} for w in ws])
    x = jax.random.normal(jax.random.PRNGKey(1), (12, d))

    out = pipeline_apply(lambda p, h: h @ p["w"], stage_params, x, mesh,
                         num_microbatches=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                               rtol=1e-5, atol=1e-6)


def test_pipelined_gpt2_matches_plain_forward():
    cfg = tiny_config().models.gpt2  # 2 layers -> 2 stages
    mesh = make_mesh(MeshConfig(dp=-1, pp=2))
    model = GPT2LM(cfg)
    ids = jax.random.randint(jax.random.PRNGKey(0), (4, 12), 0,
                             cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(1), ids)
    ref = model.apply(params, ids)
    out = pipelined_lm_forward(model, params, ids, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_moe_forward_shapes_and_routing():
    model = MoEMLP(num_experts=4, intermediate=32, capacity_factor=2.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    params = model.init(jax.random.PRNGKey(1), x)
    out = model.apply(params, x)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    # routing is input-dependent: different tokens -> different output
    out2 = model.apply(params, x * 1.5)
    assert not np.allclose(np.asarray(out), np.asarray(out2))


def test_moe_expert_parallel_matches_single_device():
    mesh = make_mesh(MeshConfig(dp=1, ep=8))
    model = MoEMLP(num_experts=8, intermediate=32, capacity_factor=2.0)
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 16, 16))
    params = model.init(jax.random.PRNGKey(3), x)
    ref = model.apply(params, x)
    sharded = shard_moe_params(params, mesh)
    out = moe_sharded_apply(model, sharded, x, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_moe_capacity_overflow_drops_tokens():
    # capacity_factor so small every expert can hold only 1 token
    model = MoEMLP(num_experts=2, intermediate=8, capacity_factor=0.01)
    x = jax.random.normal(jax.random.PRNGKey(4), (1, 8, 8))
    params = model.init(jax.random.PRNGKey(5), x)
    out = model.apply(params, x)
    assert out.shape == x.shape
    # overflowing tokens produce zero MoE output (residual fall-through)
    zero_rows = np.sum(np.all(np.asarray(out) == 0.0, axis=-1))
    assert zero_rows >= 6  # 8 tokens, <=2 kept


def test_moe_expert_parallel_train_step():
    """Gradients flow through the expert-parallel dispatch/combine (the
    GSPMD all-to-alls) and reduce a regression loss — expert-parallel
    TRAINING, not just inference."""
    import optax

    mesh = make_mesh(MeshConfig(dp=1, ep=8))
    model = MoEMLP(num_experts=8, intermediate=32, capacity_factor=2.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 16))
    y = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 16)) * 0.1
    params = shard_moe_params(
        model.init(jax.random.PRNGKey(2), x), mesh)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            out, aux = model.apply(p, x, mutable=["aux_loss"])
            lb = aux["aux_loss"]["load_balance"][0]  # sow returns a tuple
            return jnp.mean((out - y) ** 2) + 0.01 * lb

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, new_opt = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt, loss

    losses = []
    for _ in range(6):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    # expert weights stayed ep-sharded through the update
    spec = params["params"]["w1"].sharding.spec
    assert "ep" in tuple(spec), spec
