"""Spellcheck lexicon scale + suggest() quality (VERDICT round-1 item:
the reference ships a 49,569-entry hunspell dictionary and hard-blocks
misspelled guesses, reference static/script.js:435-440; this build
serves a mined wordlist and must recognize legitimate guesses at
comparable rates). Driven through the Python mirror of spell.js."""

import os
import re

import pytest

from cassmantle_tpu.server.assets import load_wordlist
from cassmantle_tpu.utils.spell import Spell

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def spell():
    return Spell(load_wordlist())


def test_wordlist_scale():
    """>=45k entries (VERDICT r4 #7; reference ships 49,569 hunspell
    entries — data/en_US.dic — whose affix flags typo.js expands at
    load; this lexicon reaches the same scale via prose mining +
    corpus-evidence-gated affix expansion, tools/build_wordlist.py)."""
    words = load_wordlist()
    assert len(words) >= 45_000, len(words)
    # guard the FILE (load_wordlist dedups, so check the raw lines)
    lines = [ln.strip() for ln in
             open(os.path.join(REPO, "data", "wordlist.txt"))
             if ln.strip()]
    assert len(lines) == len(set(lines))


COMMON = [
    # the kind of "descriptive word" guesses the game actually sees
    "stormy", "silver", "ancient", "quiet", "glass", "velvet", "bright",
    "dark", "golden", "frozen", "misty", "crimson", "gentle", "hollow",
    "amber", "silent", "distant", "burning", "shattered", "wandering",
    "river", "mountain", "forest", "ocean", "shadow", "light", "stone",
    "garden", "winter", "summer", "morning", "evening", "thunder",
    # inflected forms the stemmer must reduce
    "stories", "cities", "boxes", "stopped", "running", "quickly",
    "darker", "darkest", "flowers", "dancing", "painted", "dreams",
]


def test_check_accepts_common_words(spell):
    missing = [w for w in COMMON if not spell.check(w)]
    # a healthy lexicon + stemmer should cover essentially all of these
    assert not missing, f"lexicon misses: {missing}"


def test_check_accepts_affixed_forms(spell):
    """Prefixed, y-inflected, f-plural, and derivational forms reduce
    to a known base (the reference's typo.js consumed the full en_US
    affix grammar; VERDICT r2 flagged suffix-only coverage as a gap).
    Every case's base word is asserted in-lexicon first, so the test
    exercises the affix machinery, not the corpus."""
    cases = [
        ("unhappy", "happy"), ("rethink", "think"),
        ("misread", "read"), ("preheat", "heat"),
        ("nonhuman", "human"), ("overgrown", "grown"),
        ("outlive", "live"), ("unfolded", "fold"),
        ("happier", "happy"), ("happiest", "happy"),
        ("happily", "happy"), ("wolves", "wolf"),
        ("brightness", "bright"), ("hopeful", "hope"),
        ("stormless", "storm"), ("greenish", "green"),
        ("movement", "move"), ("drinkable", "drink"),
        ("unhappiest", "happy"),  # prefix composed with suffix
    ]
    for word, base in cases:
        assert spell.check(base), f"precondition: {base} not in lexicon"
        assert spell.check(word), f"{word} (base {base}) rejected"


def test_check_rejects_junk(spell):
    for junk in ("qzxvk", "xkcdq", "zzzzz", "aaaaaa", "qwrtpsd", ""):
        assert not spell.check(junk), junk
    assert not spell.check("storm3")   # non-alpha
    assert not spell.check("123")


def test_suggest_anchors(spell):
    """Classic one-edit typos surface the intended word in the top 5."""
    for typo, want in (
        ("stromy", "stormy"), ("silvr", "silver"), ("quietr", "quieter"),
        ("anceint", "ancient"), ("forrest", "forest"),
    ):
        got = spell.suggest(typo, 5)
        assert want in got, f"{typo}: {got}"


def test_suggest_recovers_single_edits(spell):
    """For a deterministic sample of real words, corrupt with one edit
    (delete / transpose / substitute mid-word) and require the original
    back in the top-5 suggestions for >=80% of cases."""
    words = [w for w in load_wordlist()
             if len(w) >= 6 and w.isalpha() and spell.check(w)]
    sample = words[:: max(1, len(words) // 120)][:120]
    assert len(sample) >= 80

    hits = total = 0
    for i, w in enumerate(sample):
        mid = len(w) // 2
        if i % 3 == 0:      # deletion
            typo = w[:mid] + w[mid + 1:]
        elif i % 3 == 1:    # transposition
            typo = w[:mid] + w[mid + 1] + w[mid] + w[mid + 2:]
        else:               # substitution
            sub = "q" if w[mid] != "q" else "z"
            typo = w[:mid] + sub + w[mid + 1:]
        if typo == w or spell.check(typo):
            continue        # edit landed on another real word: skip
        total += 1
        if w in spell.suggest(typo, 5):
            hits += 1
    assert total >= 40, total
    assert hits / total >= 0.8, f"{hits}/{total}"


def test_spell_rule_parity():
    """The JS and Python spellcheckers declare the same suffix rules —
    a cheap structural guard against the two drifting apart."""
    js = open(os.path.join(REPO, "static", "spell.js")).read()
    py = open(os.path.join(
        REPO, "cassmantle_tpu", "utils", "spell.py")).read()
    js_rules = set(re.findall(r'endsWith\("([a-z]+)"\)', js))
    py_rules = set(re.findall(r'endswith\("([a-z]+)"\)', py))
    assert js_rules == py_rules and js_rules
    # the doubled-consonant rule exists on both sides
    assert "bdgklmnprt" in js and "bdgklmnprt" in py
    # prefix lists match, in order (VERDICT r2: affix coverage beyond
    # suffixes — un-, re-, ... strip composably with the suffix stems)
    js_pre = re.findall(r'"([a-z]+)"',
                        re.search(r"const PREFIXES = \[(.*?)\]", js).group(1))
    py_pre = re.findall(r'"([a-z]+)"',
                        re.search(r"_PREFIXES = \((.*?)\)", py).group(1))
    assert js_pre == py_pre and len(js_pre) >= 8


def test_wordlist_endpoint_scale():
    """GET /wordlist serves the full lexicon (the client builds its
    checker from this response)."""
    import asyncio

    from tests.test_server import make_cfg, make_client

    async def run():
        client, _ = await make_client(make_cfg())
        try:
            res = await client.get("/wordlist")
            assert res.status == 200
            data = await res.json()
            assert len(data["words"]) >= 20_000
        finally:
            await client.close()

    asyncio.run(run())


def test_suggest_ranks_common_words_first(spell):
    """The served list is frequency-ordered and suggest() ranks by it:
    classic typos surface the intended word at TOP-1, and a direct
    lexicon entry always beats a stem-only construction (the stemmer
    accepts 'form'+'est', which must not outrank 'forest')."""
    for typo, want in (
        ("forrest", "forest"), ("stromy", "stormy"),
        ("silvr", "silver"), ("velvte", "velvet"),
        ("anceint", "ancient"),
    ):
        got = spell.suggest(typo, 3)
        assert got and got[0] == want, f"{typo}: {got}"


def test_wordlist_is_frequency_ordered():
    """data/wordlist.txt leads with high-frequency English (the rank
    signal suggest() relies on), not the alphabet."""
    head = [ln.strip() for ln in open(
        os.path.join(REPO, "data", "wordlist.txt")).readlines()[:50]]
    assert "the" in head and "and" in head
    assert head != sorted(head)  # not alphabetical


RARE_BUT_VALID = [
    # "zephyr"-class regression (VERDICT r4 #7): rare-but-real words a
    # player might legitimately guess must never be held as "unusual" —
    # false holds are the failure mode that matters (a false ACCEPT
    # merely skips a hint; a false hold blocks a correct guess).
    "zephyr", "zephyrs", "gossamer", "wistful", "shimmering",
    "moonlit", "starlit", "verdant", "thistle", "obsidian", "saffron",
    "quivering", "unfurled", "brambles", "mosses", "glinting",
    "lanterns", "gloaming", "dappled", "bracken", "rivulet",
    "tranquil", "burnished", "silken", "smolder", "hearth",
]


def test_no_false_holds_on_rare_valid_words(spell):
    held = [w for w in RARE_BUT_VALID if not spell.check(w)]
    assert not held, f"valid words held as unusual: {held}"


def test_doc_stopwords_rank_below_story_vocabulary():
    """Doc-corpus boilerplate ("org", "use", "software", ...) must not
    occupy the head of the frequency ranking both spellcheckers use for
    suggestion ties (VERDICT r5 weak #4): demoted words rank below
    every story word, so a one-edit typo resolves toward game
    vocabulary. Membership is preserved — the words still check."""
    import sys

    sys.path.insert(0, REPO)
    from tools.build_wordlist import DOC_STOPWORDS

    lines = [ln.strip() for ln in
             open(os.path.join(REPO, "data", "wordlist.txt"))
             if ln.strip()]
    rank = {w: i for i, w in enumerate(lines)}
    head = set(lines[:2000])
    assert not head & DOC_STOPWORDS, sorted(head & DOC_STOPWORDS)[:10]
    # demotion, not deletion
    for w in ("software", "documentation", "org"):
        assert w in rank, w
    # story vocabulary outranks every demoted word
    worst_story = max(rank[w] for w in ("stormy", "silver", "ancient",
                                        "velvet", "lantern"))
    best_demoted = min(rank[w] for w in DOC_STOPWORDS if w in rank)
    assert worst_story < best_demoted, (worst_story, best_demoted)
