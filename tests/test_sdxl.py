"""SDXL pipeline tests: dual-tower conditioning, micro-conds, batch-DP.

The reference's image generator IS remote SDXL-base (backend.py:24,
270-295); these tests cover its local TPU replacement (serving/sdxl.py) at
tiny CPU dims — geometry, determinism, and data-parallel equivalence on
the virtual 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import (
    MeshConfig,
    test_sdxl_config as _tiny_sdxl_config,
)
from cassmantle_tpu.models.clip_text import ClipTextEncoder
from cassmantle_tpu.models.unet import UNet
from cassmantle_tpu.ops.ddim import make_cfg_denoiser
from cassmantle_tpu.parallel.mesh import make_mesh
from cassmantle_tpu.serving.sdxl import SDXLPipeline


@pytest.fixture(scope="module")
def cfg():
    return _tiny_sdxl_config()


@pytest.fixture(scope="module")
def pipe(cfg):
    return SDXLPipeline(cfg)


def test_clip_penultimate_output(cfg):
    m = cfg.models.clip_text
    enc = ClipTextEncoder(m)
    ids = jnp.arange(8, dtype=jnp.int32)[None, :] % m.vocab_size
    params = enc.init(jax.random.PRNGKey(0), ids)
    out = enc.apply(params, ids)
    assert out["penultimate"].shape == out["hidden"].shape
    # penultimate is pre-final-block, pre-LN: must differ from final hidden
    assert not np.allclose(np.asarray(out["penultimate"]),
                           np.asarray(out["hidden"]))


def test_sdxl_unet_micro_conditioning(cfg):
    m = cfg.models.unet
    unet = UNet(m)
    lat = jnp.zeros((2, 8, 8, 4))
    t = jnp.zeros((2,), jnp.int32)
    ctx = jnp.zeros((2, 8, m.context_dim))
    add = jnp.ones((2, m.addition_embed_dim))
    params = unet.init(jax.random.PRNGKey(0), lat, t, ctx, add)
    eps = unet.apply(params, lat, t, ctx, add)
    assert eps.shape == lat.shape
    # micro-conditioning must actually influence the output
    eps2 = unet.apply(params, lat, t, ctx, 2.0 * add)
    assert not np.allclose(np.asarray(eps), np.asarray(eps2))


def test_cfg_denoiser_with_additions(cfg):
    m = cfg.models.unet
    unet = UNet(m)
    lat = jnp.zeros((1, 8, 8, 4))
    t = jnp.zeros((1,), jnp.int32)
    ctx = jnp.zeros((1, 8, m.context_dim))
    add = jnp.ones((1, m.addition_embed_dim))
    params = unet.init(jax.random.PRNGKey(0), lat, t, ctx, add)
    denoise = make_cfg_denoiser(
        unet.apply, params, ctx, ctx, 5.0,
        addition_embeds=add, uncond_addition_embeds=add,
    )
    eps = denoise(lat, jnp.asarray(0, jnp.int32))
    assert eps.shape == lat.shape
    assert np.isfinite(np.asarray(eps)).all()


def test_sdxl_generate_shapes_and_determinism(pipe, cfg):
    imgs = pipe.generate(["a red lighthouse", "a green meadow"], seed=7)
    s = cfg.sampler.image_size
    assert imgs.shape == (2, s, s, 3)
    assert imgs.dtype == np.uint8
    again = pipe.generate(["a red lighthouse", "a green meadow"], seed=7)
    np.testing.assert_array_equal(imgs, again)
    other = pipe.generate(["a red lighthouse", "a green meadow"], seed=8)
    assert not np.array_equal(imgs, other)


def test_sdxl_prompt_changes_image(pipe):
    a = pipe.generate(["a red lighthouse"], seed=3)
    b = pipe.generate(["an ancient forest"], seed=3)
    assert not np.array_equal(a, b)


def test_sdxl_data_parallel_matches_single_device(cfg):
    single = SDXLPipeline(cfg)
    mesh = make_mesh(MeshConfig(dp=-1, tp=1, sp=1))
    assert mesh.shape["dp"] == len(jax.devices())
    dp_pipe = SDXLPipeline(cfg, mesh=mesh)
    # full dp-width batch so both runs draw identical initial latents
    prompts = [f"scene number {i}" for i in range(mesh.shape["dp"])]
    ref = single.generate(prompts, seed=5)
    out = dp_pipe.generate(prompts, seed=5)
    assert out.shape == ref.shape
    # same params (deterministic init) + same seed -> identical images up
    # to reduction-order effects; uint8 quantization absorbs those.
    mismatch = np.mean(ref.astype(np.int32) != out.astype(np.int32))
    assert mismatch < 0.02, f"{mismatch:.4f} of pixels differ"


def test_content_backend_uses_sdxl_with_dual_towers(cfg):
    from cassmantle_tpu.serving.pipeline import TPUContentBackend
    from cassmantle_tpu.serving.sdxl import SDXLPipeline

    backend = TPUContentBackend(cfg)
    assert isinstance(backend.t2i, SDXLPipeline)
    content = backend.generate_sync("The harbor at dawn", True)
    s = cfg.sampler.image_size
    assert content.image.shape == (s, s, 3)
    assert content.prompt_text


def test_sdxl_data_parallel_pads_partial_batch(cfg):
    mesh = make_mesh(MeshConfig(dp=-1, tp=1, sp=1))
    dp_pipe = SDXLPipeline(cfg, mesh=mesh)
    s = cfg.sampler.image_size
    out = dp_pipe.generate(["a", "b", "c"], seed=1)  # 3 pads to dp width
    assert out.shape == (3, s, s, 3)


def test_sdxl_turbo_combo():
    """SDXL + the composed turbo path (dpmpp_2m + deepcache pairing):
    the shared run_cfg_denoise machinery serves the dual-tower pipeline
    too (bench entry sdxl_turbo)."""
    import dataclasses

    from cassmantle_tpu.serving.sdxl import SDXLPipeline

    cfg = _tiny_sdxl_config()
    cfg = cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, kind="dpmpp_2m", num_steps=4, deepcache=True))
    pipe = SDXLPipeline(cfg)
    imgs = pipe.generate(["a brass harbor at dusk"], seed=4)
    assert imgs.shape[-1] == 3 and imgs.dtype == np.uint8


def test_sdxl_encprop_stride1_bit_parity_and_schedule(pipe, cfg):
    """Full-pipeline encprop on the dual-tower SDXL path (the
    `sdxl_encprop` bench arm's shape): stride 1 is uint8 bit-identical
    to the plain pipeline, and a non-trivial key schedule runs end to
    end producing a (deliberately) different image."""
    import dataclasses

    from cassmantle_tpu.serving.sdxl import SDXLPipeline

    prompts = ["a tower at dusk"]
    base = pipe.generate(prompts, seed=5)
    # share_params_with: the encprop arms hold the donor's trees (the
    # sdxl_encprop bench A/B contract — one SDXL weight set in HBM)
    enc1 = SDXLPipeline(cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, encprop=True, encprop_stride=1,
        encprop_dense_steps=0)), share_params_with=pipe)
    assert enc1.unet_params is pipe.unet_params
    np.testing.assert_array_equal(base, enc1.generate(prompts, seed=5))

    enc2 = SDXLPipeline(cfg.replace(sampler=dataclasses.replace(
        cfg.sampler, encprop=True, encprop_stride=2,
        encprop_dense_steps=0)), share_params_with=pipe)
    out = enc2.generate(prompts, seed=5)
    assert out.shape == base.shape and out.dtype == np.uint8
    assert not np.array_equal(base, out)
