"""JAX dispatch-discipline lint + jit compile-count sentinel gate
(fast tier).

Golden fixture snippets pin each rule of the four
``cassmantle_tpu/analysis`` JAX passes (known violations must fail;
suppressed / static-declared / copy-fixed variants must pass), the
PR 6 ``_steps``-mirror aliasing bug is pinned as a golden pair for
``buffer-escape`` (the way PR 4 pinned the PR 1 dispatch-deadlock
shape for ``lock-order-cycle``), the repo itself must lint clean
through the real entry points (``tools/check_jax.py``,
``tools/lint_all.py``), and the ``utils/jit_sentinel`` runtime
counterpart must raise on seeded post-warmup recompiles while leaving
warmed cache hits alone.
"""

import textwrap

import pytest

from cassmantle_tpu.analysis.bufferescape import BufferEscapePass
from cassmantle_tpu.analysis.core import parse_source, run_passes
from cassmantle_tpu.analysis.envflags import EnvFlagPass
from cassmantle_tpu.analysis.hostsync import HostSyncPass
from cassmantle_tpu.analysis.recompile import RecompilePass
from cassmantle_tpu.analysis.tracerleak import TracerLeakPass
from cassmantle_tpu.utils import jit_sentinel
from cassmantle_tpu.utils.jit_sentinel import JitRecompileError


def lint(src, *passes, rel="<fixture>"):
    return run_passes([parse_source(textwrap.dedent(src), rel)],
                      list(passes))


def rules(findings):
    return [f.rule for f in findings]


# -- recompile-hazard pass ---------------------------------------------------

def test_jit_built_in_loop_fails_and_suppression_passes():
    src = """
        import jax

        def run(f, xs):
            out = []
            for x in xs:
                out.append(jax.jit(f)(x)){sup}
            return out
    """
    findings = lint(src.format(sup=""), RecompilePass())
    assert rules(findings) == ["recompile-hazard"]
    assert "inside a loop" in findings[0].message
    sup = "  # lint: ignore[recompile-hazard] — fixture reason"
    assert lint(src.format(sup=sup), RecompilePass()) == []


def test_unhashable_and_fstring_statics_fail():
    findings = lint("""
        import jax

        def f(x, mode, cfg):
            return x

        g = jax.jit(f, static_argnums=(2,), static_argnames=("mode",))

        def call(x, i):
            a = g(x, mode=f"bucket{i}")   # per-call string static
            b = g(x, "m", [1, 2])         # unhashable static
            return a, b
    """, RecompilePass())
    assert rules(findings) == ["recompile-hazard"] * 2
    assert any("f-string" in f.message for f in findings)
    assert any("unhashable" in f.message for f in findings)


def test_plain_hashable_statics_are_clean():
    assert lint("""
        import jax

        def f(x, mode):
            return x

        g = jax.jit(f, static_argnames=("mode",))

        def call(x):
            return g(x, mode="bucket8")
    """, RecompilePass()) == []


def test_mutable_attr_capture_fails_lazy_init_is_clean():
    findings = lint("""
        import jax

        class P:
            def __init__(self):
                self._scale = 1.0
                self._fn = jax.jit(self._impl)

            def set_scale(self, s):
                self._scale = s          # reassigns constructed state

            def _impl(self, x):
                return x * self._scale   # baked in at trace time
    """, RecompilePass())
    assert rules(findings) == ["recompile-hazard"]
    assert "self._scale" in findings[0].message
    # one-shot lazy init (assigned once, outside __init__, never in
    # __init__) is a construction pattern, not mutation
    assert lint("""
        import jax

        class P:
            def _ensure(self):
                self.enc = make_encoder()

            def _impl(self, x):
                return self.enc.apply(x)

            def build(self):
                self._fn = jax.jit(self._impl)
    """, RecompilePass()) == []


def test_unbucketed_slice_into_jit_in_loop_fails():
    findings = lint("""
        import jax

        def f(x):
            return x

        g = jax.jit(f)

        def run(xs, lens):
            out = []
            for i, n in enumerate(lens):
                out.append(g(xs[i][:n]))
            return out
    """, RecompilePass())
    assert rules(findings) == ["recompile-hazard"]
    assert "bucket ladder" in findings[0].message


def test_shape_scalar_branched_on_by_callee_fails():
    findings = lint("""
        import jax

        def f(x, n):
            if n:
                return x
            return x * 2

        g = jax.jit(f)

        def call(x):
            return g(x, len(x))
    """, RecompilePass())
    assert rules(findings) == ["recompile-hazard"]
    assert "branches on it" in findings[0].message


def test_static_positions_map_through_partial_bound_args():
    """A ``jax.jit(partial(self._impl, k), static_argnames=...)`` alias
    offsets call-site positions past the partial-bound params: the
    f-string landing in the declared-static slot is flagged, and a
    traced arg at call position 0 is NOT mistaken for the bound
    static."""
    src = """
        import jax
        from functools import partial

        class P:
            def __init__(self, k):
                self._fn = jax.jit(partial(self._impl, k),
                                   static_argnames=("mode",))

            def _impl(self, k, x, mode):
                return x

            def call(self, x, i):
                return self._fn({args})
    """
    bad = lint(src.format(args='x, f"bucket{i}"'), RecompilePass())
    assert rules(bad) == ["recompile-hazard"]
    assert "f-string" in bad[0].message
    # the traced call position 0 maps to param 'x', not the bound 'k'
    assert lint(src.format(args="x, mode='m'"), RecompilePass()) == []


def test_multi_site_statics_do_not_cross_contaminate_aliases():
    """One function jitted at two sites with different statics: the
    plain alias's traced positions must not inherit the other site's
    static declarations (a traced list pytree is legal there)."""
    src = """
        import jax

        def f(x, cfg):
            return x

        g1 = jax.jit(f)
        g2 = jax.jit(f, static_argnums=(1,))

        def call(x):
            a = g1(x, [1, 2])    # traced pytree: legal
            b = g2(x, {target})
            return a, b
    """
    clean = lint(src.format(target='("t",)'), RecompilePass())
    assert clean == []
    bad = lint(src.format(target="[1, 2]"), RecompilePass())
    assert rules(bad) == ["recompile-hazard"]
    assert "'g2'" in bad[0].message


def test_decorated_method_static_argnums_count_self():
    """jax jits a DECORATED method unbound — ``self`` is position 0,
    so ``static_argnums=(1,)`` names the first real parameter."""
    src = """
        import jax
        from functools import partial

        class P:
            @partial(jax.jit, static_argnums=({idx},))
            def f(self, n, x):
                if n:
                    return x
                return -x
    """
    # index 1 == n: the branch is on a static — clean
    assert lint(src.format(idx=1), TracerLeakPass()) == []
    # index 2 == x: n stays traced, the branch is a trace error
    findings = lint(src.format(idx=2), TracerLeakPass())
    assert rules(findings) == ["tracer-leak"]
    assert "'n'" in findings[0].message


def test_false_positive_shapes_stay_clean():
    """FP regression pins: (a) a constant-width sliding window in a
    loop has ONE shape; (b) branchy one-shot lazy init inside a single
    ``_ensure`` method is construction, not mutation; (c) two classes
    sharing an attribute name with different jit signatures make the
    alias ambiguous — dropped, not misattributed."""
    assert lint("""
        import jax

        def f(x):
            return x

        g = jax.jit(f)

        def run(xs, n):
            out = []
            for off in range(0, n, 128):
                out.append(g(xs[off:off + 128]))
            return out
    """, RecompilePass()) == []
    assert lint("""
        import jax

        class P:
            def _ensure(self, use_flash):
                if use_flash:
                    self.enc = FlashEnc()
                else:
                    self.enc = XlaEnc()

            def _impl(self, x):
                return self.enc(x)

            def build(self):
                self._fn = jax.jit(self._impl)
    """, RecompilePass()) == []
    assert lint("""
        import jax

        def f(x, cfg):
            return x

        def h(x, y):
            return x

        class A:
            def __init__(self):
                self._fn = jax.jit(f, static_argnums=(1,))

        class B:
            def __init__(self):
                self._fn = jax.jit(h)

            def call(self, x):
                return self._fn(x, [1, 2])   # h's traced pytree: legal
    """, RecompilePass()) == []


def test_quant_tree_transform_in_loop_fails_and_suppression_passes():
    """Golden fixture for ``quant-in-dispatch`` (ISSUE 20): the
    quantize-inside-dispatch-loop hazard — w8a8_tree_host re-run per
    generate call re-quantizes the whole param tree per iteration."""
    src = """
        from cassmantle_tpu.ops.quant import w8a8_tree_host

        def serve(pipe, requests):
            for req in requests:
                params = w8a8_tree_host(pipe.unet_params){sup}
                pipe.generate(req.prompts, params=params)
    """
    findings = lint(src.format(sup=""), RecompilePass())
    assert rules(findings) == ["quant-in-dispatch"]
    assert "re-quantizes the whole param tree" in findings[0].message
    sup = "  # lint: ignore[quant-in-dispatch] — fixture reason"
    assert lint(src.format(sup=sup), RecompilePass()) == []


def test_quant_tree_transform_in_jit_fails():
    """Dotted form inside a jit-traced closure: the requantize is
    baked into the compiled graph and re-executes per dispatch."""
    findings = lint("""
        import jax
        from cassmantle_tpu.ops import quant

        @jax.jit
        def denoise(params, latents):
            qparams = quant.w8a8_tree(params)
            return apply(qparams, latents)
    """, RecompilePass())
    assert rules(findings) == ["quant-in-dispatch"]
    assert "jit-traced" in findings[0].message


def test_quant_tree_transform_at_load_is_clean():
    """The contract-conforming shape — quantize ONCE in the loader
    transform (serving/pipeline.py w8a8_unet_tools) — plus a partial
    reference (not a call) threaded into a loader, and an unrelated
    call named like a transform member but outside loop/jit."""
    assert lint("""
        from functools import partial

        from cassmantle_tpu.ops.quant import (
            quantize_tree_host,
            w8a8_tree_host,
        )

        def w8a8_tools(cfg, scales):
            return lambda params: w8a8_tree_host(
                params, act_scales=scales)

        def build(loader, cfg):
            transform = partial(w8a8_tree_host, predicate=None)
            params = loader(transform)
            donor = quantize_tree_host(params)
            return donor
    """, RecompilePass()) == []


def test_host_concrete_jax_calls_in_conditions_are_clean():
    """jax host APIs (default_backend, devices) are concrete at trace
    time — only jnp.* array results trip the condition check."""
    assert lint("""
        import jax

        @jax.jit
        def f(x):
            if jax.default_backend() == "cpu":
                return x
            return x * 2
    """, TracerLeakPass()) == []


# -- tracer-leak pass --------------------------------------------------------

def test_store_to_self_in_jit_fails():
    findings = lint("""
        import jax

        class P:
            def build(self):
                self._fn = jax.jit(self._impl)

            def _impl(self, x):
                self.last = x
                return x
    """, TracerLeakPass())
    assert rules(findings) == ["tracer-leak"]
    assert "self.last" in findings[0].message


def test_append_to_outer_container_in_jit_fails():
    findings = lint("""
        import jax

        acc = []

        @jax.jit
        def f(x):
            acc.append(x)
            return x
    """, TracerLeakPass())
    assert rules(findings) == ["tracer-leak"]
    assert "acc" in findings[0].message


def test_pure_update_result_used_is_clean():
    """optax-style ``updates, s = opt.update(...)`` is a pure
    functional API — only bare-statement mutator calls are container
    mutations."""
    assert lint("""
        import jax

        class T:
            def build(self):
                self._step = jax.jit(self._impl)

            def _impl(self, params, opt_state, grads):
                updates, new_opt = self.optimizer.update(
                    grads, opt_state, params)
                return updates, new_opt
    """, TracerLeakPass()) == []


def test_branch_on_traced_param_fails_static_is_clean():
    src = """
        import jax
        from functools import partial

        @partial(jax.jit{statics})
        def f(x, mode):
            if mode:
                return x
            return -x
    """
    bad = lint(src.format(statics=""), TracerLeakPass())
    assert rules(bad) == ["tracer-leak"]
    assert "mode" in bad[0].message
    clean = lint(src.format(statics=", static_argnums=(1,)"),
                 TracerLeakPass())
    assert clean == []


def test_concrete_guards_on_traced_params_are_clean():
    assert lint("""
        import jax

        @jax.jit
        def f(x, y):
            if y is None:
                return x
            if x.shape[0] > 4:
                return x + y
            if len(x) > 2:
                return x - y
            return x
    """, TracerLeakPass()) == []


def test_jnp_result_in_while_condition_fails():
    findings = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            while jnp.any(x > 0):
                x = x - 1
            return x
    """, TracerLeakPass())
    assert rules(findings) == ["tracer-leak"]
    assert "lax.cond" in findings[0].message


# -- buffer-escape pass: the PR 6 _steps aliasing bug, pinned ----------------

_STEPS_MIRROR_SRC = """
    import numpy as np
    import jax.numpy as jnp

    class StagedServer:
        def __init__(self, capacity):
            self._steps = np.zeros((capacity,), dtype=np.int32)

        def _denoise_tick(self):
            idx = jnp.asarray(self._steps{copy})
            self._dispatch(idx)
            self._note_step()

        def _note_step(self):
            self._steps[0] += 1
"""


def test_pr6_steps_mirror_aliasing_shape_is_caught():
    """Regression fixture: the PR 6 silently-wrong-images bug — the
    ``_steps`` numpy mirror handed to ``jnp.asarray`` (zero-copy alias
    on the CPU backend) while ``_note_step`` mutates it in place right
    after the async dispatch. The shipped ``.copy()`` fix is the clean
    variant."""
    findings = lint(_STEPS_MIRROR_SRC.format(copy=""),
                    BufferEscapePass())
    assert rules(findings) == ["buffer-escape"]
    assert "self._steps" in findings[0].message
    assert ".copy()" in findings[0].message


def test_pr6_steps_mirror_copy_fix_is_clean():
    assert lint(_STEPS_MIRROR_SRC.format(copy=".copy()"),
                BufferEscapePass()) == []


def test_mirror_into_executor_submit_fails_and_suppression_passes():
    src = """
        import numpy as np

        class W:
            def __init__(self, ex):
                self._mask = np.zeros((8,), dtype=bool)
                self._ex = ex

            def kick(self):
                fut = self._ex.submit(work, self._mask){sup}
                self._mask[0] = True
                return fut
    """
    findings = lint(src.format(sup=""), BufferEscapePass())
    assert rules(findings) == ["buffer-escape"]
    sup = "  # lint: ignore[buffer-escape] — fixture reason"
    assert lint(src.format(sup=sup), BufferEscapePass()) == []


_SKIP_CACHE_MIRROR_SRC = """
    import numpy as np
    import jax.numpy as jnp

    class EncpropScheduler:
        # encoder-propagation cache host mirror: the skip-stack shape
        # retained across denoise steps (ISSUE 11). The shipped serving
        # loop keeps the cache purely ON DEVICE inside one scan (no
        # host mirror exists to alias); this fixture pins the hazard a
        # host-mirrored variant would reintroduce.
        def __init__(self, capacity, width):
            self._skip_cache = np.zeros((capacity, width),
                                        dtype=np.float32)

        def step(self):
            cache = jnp.asarray(self._skip_cache{copy})
            self._dispatch(cache)
            self._refresh_keys()

        def _refresh_keys(self):
            self._skip_cache[0] += 1.0
"""


def test_encprop_skip_cache_mirror_shape_is_caught():
    """Golden fixture for the encprop skip-stack cache shape: a numpy
    mirror of per-step encoder features handed to ``jnp.asarray``
    (zero-copy alias on CPU) and then mutated by the next key-step
    refresh — exactly the buffer-escape/tracer-leak territory the PR 7
    passes exist for. The ``.copy()`` variant is the clean shape."""
    findings = lint(_SKIP_CACHE_MIRROR_SRC.format(copy=""),
                    BufferEscapePass())
    assert rules(findings) == ["buffer-escape"]
    assert "self._skip_cache" in findings[0].message


def test_encprop_skip_cache_copy_fix_is_clean():
    assert lint(_SKIP_CACHE_MIRROR_SRC.format(copy=".copy()"),
                BufferEscapePass()) == []


def test_unmutated_mirror_and_host_reads_are_clean():
    assert lint("""
        import numpy as np
        import jax.numpy as jnp

        class S:
            def __init__(self):
                self._alive = np.zeros((8,), dtype=bool)
                self._consts = np.arange(8)

            def tick(self):
                live = np.flatnonzero(self._alive)   # host read: no sink
                return jnp.asarray(self._consts)     # never mutated
    """, BufferEscapePass()) == []


# -- host-sync: the distill-loop shape (ISSUE 15) ----------------------------

_DISTILL_LOOP_SRC = """
    import numpy as np
    import jax.numpy as jnp

    def distill(trainer, student, ema, opt, teacher, batches, rng):
        losses = []
        for batch in batches:
            student, ema, opt, loss = trainer.step(
                student, ema, opt, teacher, batch, rng)
            losses.append({loss_expr})
        return student, ema, {collect}
"""


def test_distill_loop_host_sync_per_step_fails():
    """Golden fixture pinning the distill-loop shape: transferring the
    loss to host EVERY train step (``float(loss)`` per iteration)
    serializes the device pipeline on the training hot loop — exactly
    the per-iteration sync the host-sync pass exists for. The trainer's
    own step API documents the clean shape (parallel/train.py)."""
    findings = lint(
        _DISTILL_LOOP_SRC.format(loss_expr="float(loss)",
                                 collect="losses"),
        HostSyncPass())
    assert rules(findings) == ["host-sync"]
    assert "float(" in findings[0].message


def test_distill_loop_collect_once_is_clean():
    """The clean counterpart: device scalars accumulate in the loop
    and ONE boundary transfer lands the whole curve."""
    assert lint(
        _DISTILL_LOOP_SRC.format(
            loss_expr="loss",
            collect="np.asarray(jnp.stack(losses))"),
        HostSyncPass()) == []


# -- env-flag registry pass --------------------------------------------------

_REG = {"CASSMANTLE_DOCUMENTED": 42}


def test_undocumented_env_read_fails_documented_passes():
    src = """
        import os

        A = os.environ.get("CASSMANTLE_DOCUMENTED", "")
        B = os.environ.get("CASSMANTLE_MYSTERY", "")
    """
    findings = lint(src, EnvFlagPass(registry=dict(_REG),
                                     check_orphans=False))
    assert rules(findings) == ["env-flag"]
    assert "CASSMANTLE_MYSTERY" in findings[0].message


def test_env_reads_resolve_consts_helpers_and_subscripts():
    src = """
        import os

        _PROBE = "CASSMANTLE_PROBE"

        def _block_env(name, default):
            return default

        A = os.environ.get(_PROBE)
        B = _block_env("CASSMANTLE_TILE", 1024)
        C = os.environ["CASSMANTLE_RAW"]
        os.environ[_PROBE] = "cached"
    """
    reg = {"CASSMANTLE_PROBE": 1, "CASSMANTLE_TILE": 2,
           "CASSMANTLE_RAW": 3}
    assert lint(src, EnvFlagPass(registry=reg,
                                 check_orphans=False)) == []
    # against a foreign registry every READ is undocumented
    findings = lint(src, EnvFlagPass(registry={"CASSMANTLE_OTHER": 1},
                                     check_orphans=False))
    assert {f.message.split()[0] for f in findings} == \
        {"CASSMANTLE_PROBE", "CASSMANTLE_TILE", "CASSMANTLE_RAW"}


def test_env_write_alone_does_not_satisfy_the_registry():
    """A flag that is only ever ASSIGNED (exported for children) is not
    a read — its registry row stays reportable as stale."""
    findings = lint("""
        import os

        os.environ["CASSMANTLE_EXPORTED"] = "1"
    """, EnvFlagPass(registry={"CASSMANTLE_EXPORTED": 9}))
    assert rules(findings) == ["env-flag"]
    assert "never read" in findings[0].message


def test_stale_registry_row_reported_by_finalize():
    findings = lint("""
        import os

        A = os.environ.get("CASSMANTLE_DOCUMENTED", "")
    """, EnvFlagPass(registry={"CASSMANTLE_DOCUMENTED": 1,
                               "CASSMANTLE_GHOST": 7}))
    assert rules(findings) == ["env-flag"]
    assert "CASSMANTLE_GHOST" in findings[0].message
    assert findings[0].path == "docs/DEPLOY.md"
    assert findings[0].lineno == 7


# -- the repo itself lints clean ---------------------------------------------

def test_repo_is_jax_clean():
    from tools.check_jax import check

    assert check() == []


def test_check_jax_cli_exits_zero():
    from tools.check_jax import main

    assert main([]) == 0


def test_lint_all_includes_jax_passes(tmp_path):
    """The aggregate gate stays green on the package and goes red on a
    tree seeding a recompile hazard + a buffer escape — proving
    lint_all actually runs the jax passes in its one walk."""
    from tools.lint_all import main

    assert main([]) == 0
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax
        import numpy as np
        import jax.numpy as jnp

        def run(f, xs):
            return [jax.jit(f)(x) for x in xs]

        class S:
            def __init__(self):
                self._steps = np.zeros((4,), dtype=np.int32)

            def tick(self):
                idx = jnp.asarray(self._steps)
                self._steps[0] += 1
                return idx
    """))
    assert main([str(bad.parent)]) == 1


def test_new_rules_documented():
    import pathlib

    doc = pathlib.Path(__file__).resolve().parents[1] / "docs" / \
        "STATIC_ANALYSIS.md"
    text = doc.read_text()
    for rule in ("recompile-hazard", "tracer-leak", "buffer-escape",
                 "env-flag"):
        assert rule in text, f"rule {rule} missing from catalog"
    assert "jit_sentinel" in text
    assert "CASSMANTLE_JIT_SENTINEL" in text


# -- jit compile-count sentinel (runtime counterpart) ------------------------
# (the autouse conftest fixture arms the sentinel + resets counts)

def test_seeded_post_warmup_recompile_raises():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fn(x):
        return x * 2 + 1

    fn(jnp.ones((3,)))                       # warmup compile
    assert jit_sentinel.compiles("fn") == 1
    with pytest.raises(JitRecompileError) as exc:
        with jit_sentinel.no_new_compiles():
            fn(jnp.ones((7,)))               # new shape: recompiles
    assert "fn" in str(exc.value)


def test_warmed_cache_hits_pass_the_assertion():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def fn(x):
        return x - 1

    fn(jnp.ones((4,)))
    with jit_sentinel.no_new_compiles():
        for _ in range(3):
            fn(jnp.ones((4,)))               # cache hits only
    assert jit_sentinel.compiles("fn") == 1


def test_only_and_allow_filters():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def watched(x):
        return x + 2

    @jax.jit
    def unwatched(x):
        return x + 3

    watched(jnp.ones((2,)))
    # an unrelated function may compile inside a window scoped to
    # 'watched' names only
    with jit_sentinel.no_new_compiles(only=("watched",)):
        unwatched(jnp.ones((2,)))
    # ...and allow= exempts an expected cold bucket by name
    with jit_sentinel.no_new_compiles(allow=("unwatched",
                                             "convert_element_type",
                                             "broadcast_in_dim")):
        unwatched(jnp.ones((6,)))


def test_recompile_counts_metrics_and_flight_recorder():
    import jax
    import jax.numpy as jnp

    from cassmantle_tpu.obs.recorder import flight_recorder
    from cassmantle_tpu.utils.logging import metrics

    @jax.jit
    def fn(x):
        return x * 5

    before = metrics.snapshot()["counters"].get("jit.recompiles", 0)
    fn(jnp.ones((2,)))
    fn(jnp.ones((9,)))                       # recompile
    after = metrics.snapshot()["counters"]["jit.recompiles"]
    assert after >= before + 1
    kinds = [e["kind"] for e in flight_recorder.tail(50)]
    assert "jit.recompile" in kinds


def test_disabled_sentinel_is_vacuous():
    import jax
    import jax.numpy as jnp

    jit_sentinel.disable_sentinel()
    try:
        assert not jit_sentinel.sentinel_active()

        @jax.jit
        def fn(x):
            return x / 2

        with jit_sentinel.no_new_compiles():
            fn(jnp.ones((3,)))               # compile, unobserved
        assert jit_sentinel.compiles() == 0  # nothing counted either
    finally:
        jit_sentinel.enable_sentinel()       # autouse fixture disarms


def test_env_arming_is_wired_through_compile_cache(monkeypatch):
    """CASSMANTLE_JIT_SENTINEL=1 arms log-only counting on any
    pipeline/scorer boot (they all call enable_compile_cache)."""
    from cassmantle_tpu.utils.compile_cache import enable_compile_cache

    jit_sentinel.disable_sentinel()
    try:
        monkeypatch.setenv("CASSMANTLE_JIT_SENTINEL", "0")
        jit_sentinel.maybe_enable_from_env()
        assert not jit_sentinel.sentinel_active()
        monkeypatch.setenv("CASSMANTLE_JIT_SENTINEL", "1")
        enable_compile_cache()
        assert jit_sentinel.sentinel_active()
    finally:
        jit_sentinel.enable_sentinel()       # leave armed for fixture
