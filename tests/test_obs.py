"""Observability subsystem tests (ISSUE 3): tracer propagation, metrics
histograms + Prometheus exposition, flight recorder, and the end-to-end
acceptance paths (X-Trace-Id through the queue to device stage spans;
/debugz replaying a breaker-trip -> reserve-rotation -> recovery story).
"""

import asyncio
import dataclasses
import json
import logging as stdlog
import threading

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.obs.recorder import FlightRecorder, flight_recorder
from cassmantle_tpu.obs.trace import Tracer, run_with_ctx, tracer
from cassmantle_tpu.utils.logging import JsonLogFormatter, Metrics


def make_cfg(rate=1000.0):
    cfg = _tiny_config()
    return cfg.replace(game=dataclasses.replace(
        cfg.game, rate_limit_default=rate, rate_limit_api=rate,
        time_per_prompt=30.0,
    ))


# -- metrics registry ------------------------------------------------------

def test_histogram_percentiles_unbiased():
    """Bucketed percentiles are all-time (no sliding-window trim) and
    interpolate inside the bucket — the old keep-last-1024 list made
    p50/p99 window stats and mis-indexed p99 at small n."""
    m = Metrics()
    buckets = tuple((i + 1) / 10 for i in range(10))    # 0.1 .. 1.0
    for i in range(1, 101):
        m.observe("t.lat_s", i / 100, buckets=buckets)
    snap = m.snapshot()["timings"]["t.lat_s"]
    assert set(snap) == {"count", "mean_s", "p50_s", "p99_s"}
    assert snap["count"] == 100
    assert abs(snap["mean_s"] - 0.505) < 1e-9
    assert abs(snap["p50_s"] - 0.5) < 1e-9
    assert abs(snap["p99_s"] - 0.99) < 1e-9


def test_histogram_small_n_sane():
    """n=1: both percentiles land inside the single value's bucket (the
    old code's int(n*0.99) indexed sample 0 — the MINIMUM — as p99)."""
    m = Metrics()
    m.observe("t.one_s", 0.3, buckets=(0.25, 0.5, 1.0))
    snap = m.snapshot()["timings"]["t.one_s"]
    assert 0.25 < snap["p50_s"] <= 0.5
    assert 0.25 < snap["p99_s"] <= 0.5
    assert snap["p99_s"] >= snap["p50_s"]


def test_histogram_memory_bounded():
    m = Metrics()
    for i in range(5000):
        m.observe("t.mem_s", float(i), buckets=(1.0, 10.0))
    hist = m._hists[("t.mem_s", ())]
    assert len(hist.counts) == 3                 # 2 bounds + overflow
    assert m.snapshot()["timings"]["t.mem_s"]["count"] == 5000


def test_prometheus_exposition_golden():
    m = Metrics(default_buckets=(0.5, 1.0))
    m.inc("t.hits")
    m.inc("t.hits", 2)
    m.inc("t.labeled", labels={"queue": "score"})
    m.gauge("t.depth", 3)
    for v in (0.25, 0.5, 2.0):
        m.observe("t.lat_s", v)
    assert m.prometheus() == (
        "# TYPE cassmantle_t_hits_total counter\n"
        "cassmantle_t_hits_total 3\n"
        "# TYPE cassmantle_t_labeled_total counter\n"
        'cassmantle_t_labeled_total{queue="score"} 1\n'
        "# TYPE cassmantle_t_depth gauge\n"
        "cassmantle_t_depth 3\n"
        "# TYPE cassmantle_t_lat_seconds histogram\n"
        'cassmantle_t_lat_seconds_bucket{le="0.5"} 2\n'
        'cassmantle_t_lat_seconds_bucket{le="1"} 2\n'
        'cassmantle_t_lat_seconds_bucket{le="+Inf"} 3\n'
        "cassmantle_t_lat_seconds_sum 2.75\n"
        "cassmantle_t_lat_seconds_count 3\n"
    )


def test_snapshot_json_shape_backward_compatible():
    """The pre-histogram consumers (tests, __main__, dashboards) read
    flat counters/gauges and count/mean_s/p50_s/p99_s timings."""
    m = Metrics()
    m.inc("a.b")
    m.gauge("c.d", 1.0)
    m.observe("e.f_s", 0.1)
    snap = m.snapshot()
    assert set(snap) == {"counters", "gauges", "timings"}
    assert snap["counters"] == {"a.b": 1.0}
    assert snap["gauges"] == {"c.d": 1.0}
    assert set(snap["timings"]["e.f_s"]) == \
        {"count", "mean_s", "p50_s", "p99_s"}
    # labeled series key as name{k="v"} without disturbing plain names
    m.inc("a.b", labels={"q": "x"})
    assert m.snapshot()["counters"]['a.b{q="x"}'] == 1.0


# -- logger fixes ----------------------------------------------------------

def test_get_logger_single_handler_under_contention():
    """The double-handler race: N threads racing the first get_logger
    must end with exactly ONE handler (duplicated handlers duplicate
    every log line for the process lifetime)."""
    from cassmantle_tpu.utils.logging import get_logger

    root = stdlog.getLogger("cassmantle")
    for h in root.handlers[:]:
        root.removeHandler(h)
    barrier = threading.Barrier(16)

    def worker():
        barrier.wait()
        get_logger("race")

    threads = [threading.Thread(target=worker) for _ in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(root.handlers) == 1


def test_json_log_format_injects_trace_id(monkeypatch):
    fmt = JsonLogFormatter()
    record = stdlog.LogRecord(
        name="cassmantle.x", level=stdlog.INFO, pathname=__file__,
        lineno=1, msg="hello %s", args=("world",), exc_info=None)
    with tracer.span("t.json", root=True) as h:
        line = fmt.format(record)
    data = json.loads(line)
    assert data["msg"] == "hello world"
    assert data["level"] == "INFO"
    assert data["trace_id"] == h.trace_id
    # outside any trace: the key is simply absent
    assert "trace_id" not in json.loads(fmt.format(record))

    # the env switch installs the JSON formatter on first handler attach
    from cassmantle_tpu.utils.logging import get_logger

    monkeypatch.setenv("CASSMANTLE_LOG_FORMAT", "json")
    root = stdlog.getLogger("cassmantle")
    old = root.handlers[:]
    for h2 in old:
        root.removeHandler(h2)
    try:
        get_logger("jsontest")
        assert isinstance(root.handlers[0].formatter, JsonLogFormatter)
    finally:
        for h2 in root.handlers[:]:
            root.removeHandler(h2)
        for h2 in old:
            root.addHandler(h2)


# -- tracer ----------------------------------------------------------------

def test_span_nesting_and_parent_ids():
    tr = Tracer(capacity=8)
    with tr.span("a.root", root=True) as root:
        with tr.span("a.child") as child:
            assert child.trace_id == root.trace_id
    spans = {s["name"]: s for s in tr.get_trace(root.trace_id)}
    assert spans["a.child"]["parent_id"] == root.span_id
    assert spans["a.root"]["parent_id"] is None
    assert spans["a.child"]["duration_s"] >= 0.0


def test_trace_ring_evicts_oldest():
    tr = Tracer(capacity=2)
    handles = []
    for i in range(3):
        with tr.span("a.b", root=True) as h:
            handles.append(h)
    assert tr.get_trace(handles[0].trace_id) is None
    assert tr.get_trace(handles[1].trace_id) is not None
    assert tr.get_trace(handles[2].trace_id) is not None


def test_trace_ring_is_lru_and_never_resurrects_evicted():
    """Activity protects a long-running trace from bursts of short ones
    (true LRU, not FIFO), and a late span from an ALREADY-evicted trace
    is dropped rather than resurrecting a torn partial trace."""
    import time as _time

    tr = Tracer(capacity=2)
    long_running = tr.new_root_ctx()
    tr.record_span("w.early", tr.child_ctx(long_running),
                   start_wall=_time.time(), duration_s=0.0)
    with tr.span("a.b", root=True):          # ring: [long, b]
        pass
    # a new span refreshes the long trace's LRU slot...
    tr.record_span("w.mid", tr.child_ctx(long_running),
                   start_wall=_time.time(), duration_s=0.0)
    with tr.span("a.c", root=True):          # evicts b, not long
        pass
    assert tr.get_trace(long_running.trace_id) is not None
    # ...and once genuinely evicted, it stays gone
    evicted = tr.new_root_ctx()
    tr.record_span("w.x", tr.child_ctx(evicted),
                   start_wall=_time.time(), duration_s=0.0)
    for _ in range(3):
        with tr.span("a.flood", root=True):
            pass
    assert tr.get_trace(evicted.trace_id) is None
    tr.record_span("w.late", tr.child_ctx(evicted),
                   start_wall=_time.time(), duration_s=0.0)
    assert tr.get_trace(evicted.trace_id) is None     # no torn revival


def test_degraded_status_events_are_opt_in():
    """The flight-recorder tail is internal state: status() embeds it
    only when the HTTP layer vouches the caller is loopback."""
    from cassmantle_tpu.serving.supervisor import ServingSupervisor

    sup = ServingSupervisor()
    for _ in range(sup.content_breaker.failure_threshold):
        sup.content_breaker.record_failure()
    assert "events" not in sup.status()                      # default
    assert "events" in sup.status(include_events=True)       # operator
    sup.content_breaker.record_success()
    assert "events" not in sup.status(include_events=True)   # healthy


def test_unsampled_trace_propagates_ids_but_records_nothing():
    tr = Tracer(capacity=8, sample_rate=0.0)
    with tr.span("a.b", root=True) as h:
        assert h.trace_id                      # header stays useful
        with tr.span("a.c") as c:
            assert c.trace_id == h.trace_id
    assert tr.get_trace(h.trace_id) is None


def test_ctx_crosses_threads_explicitly():
    """run_with_ctx is the dispatch-thread seam: a span opened on a
    foreign thread under a carried ctx parents correctly."""
    tr = Tracer(capacity=8)
    out = {}

    def on_thread():
        with tr.span("a.stage") as s:
            out["trace"] = s.trace_id

    with tr.span("a.root", root=True) as root:
        t = threading.Thread(
            target=run_with_ctx, args=(root.ctx, on_thread))
        t.start()
        t.join()
    assert out["trace"] == root.trace_id
    spans = {s["name"]: s for s in tr.get_trace(root.trace_id)}
    assert spans["a.stage"]["parent_id"] == root.span_id


def test_error_spans_marked():
    tr = Tracer(capacity=8)
    with pytest.raises(ValueError):
        with tr.span("a.bad", root=True) as h:
            raise ValueError("boom")
    (span,) = tr.get_trace(h.trace_id)
    assert span["status"] == "error"


# -- flight recorder -------------------------------------------------------

def test_flight_recorder_capacity_and_ordering():
    r = FlightRecorder(capacity=4)
    for i in range(10):
        r.record("t.event", i=i)
    tail = r.tail()
    assert [e["i"] for e in tail] == [6, 7, 8, 9]
    assert [e["seq"] for e in tail] == [7, 8, 9, 10]     # monotonic
    assert r.stats()["dropped"] == 6
    assert [e["i"] for e in r.tail(2)] == [8, 9]
    r.record("other.kind")
    assert all(e["kind"] == "t.event" for e in r.tail(kind="t.event"))
    assert [e["kind"] for e in r.tail(kind="other.")] == ["other.kind"]
    r.set_capacity(2)
    assert [e["kind"] for e in r.tail()] == ["t.event", "other.kind"]


# -- queue split (unit) ----------------------------------------------------

@pytest.mark.asyncio
async def test_queue_records_wait_service_split_and_marks():
    from cassmantle_tpu.serving.queue import BatchingQueue

    q = BatchingQueue(lambda items: [x * 2 for x in items],
                      max_delay_ms=5, name="obsq")

    async def request():
        with tracer.span("req.root", root=True) as h:
            result = await q.submit(21)
            return h, result

    handle, result = await request()
    await q.stop()
    assert result == 42
    spans = {s["name"]: s for s in tracer.get_trace(handle.trace_id)}
    # member-side split + the batch span joined into the same trace
    # (single-request batch)
    assert "obsq.queue_wait" in spans and "obsq.batch_service" in spans
    assert spans["obsq.batch"]["attrs"]["batch_size"] == 1
    assert spans["obsq.queue_wait"]["parent_id"] == handle.span_id
    link = spans["obsq.batch_service"]["attrs"]
    assert link["batch_span"] == spans["obsq.batch"]["span_id"]
    # the marks blackboard carries the same split for response headers
    assert handle.ctx.marks["queue_wait_s"] >= 0.0
    assert handle.ctx.marks["service_s"] >= 0.0


def test_span_cap_truncates_honestly():
    """Past max_spans_per_trace the drop is counted and the trace is
    visibly marked truncated — never a silently-shortened trace."""
    tr = Tracer(capacity=4, max_spans_per_trace=2)
    with tr.span("c.root", root=True) as h:
        for _ in range(3):
            with tr.span("c.child"):
                pass
    spans = tr.get_trace(h.trace_id)
    assert len(spans) == 2
    assert spans[-1]["attrs"]["truncated"] is True


@pytest.mark.asyncio
async def test_expired_deadline_still_observes_queue_wait():
    """The queue_wait_s histogram must include waits that EXPIRED —
    excluding them would report healthy p99s exactly while users time
    out behind a wedged device."""
    from cassmantle_tpu.serving.queue import BatchingQueue, DeadlineExceeded
    from cassmantle_tpu.utils.logging import metrics

    q = BatchingQueue(lambda items: items, name="expq")
    q.start()
    await q.stop()
    q._task = object()        # collector never drains (test_queue idiom)
    with pytest.raises(DeadlineExceeded):
        await q.submit(1, deadline_s=0.02)
    snap = metrics.snapshot()
    assert snap["counters"]["expq.deadline_expired"] >= 1
    wait = snap["timings"]["expq.queue_wait_s"]
    assert wait["count"] >= 1
    assert wait["p99_s"] >= 0.0


@pytest.mark.asyncio
async def test_untraced_submits_mint_no_orphan_batch_traces():
    """A batch whose members carry no trace ctx records nothing — it
    must not mint a root trace per batch and flush the bounded ring."""
    from cassmantle_tpu.serving.queue import BatchingQueue

    before = set(tracer.trace_ids())
    q = BatchingQueue(lambda items: items, name="orphq", max_delay_ms=1)
    assert await q.submit(7) == 7       # submitted outside any trace
    await q.stop()
    new = set(tracer.trace_ids()) - before
    assert not new


@pytest.mark.asyncio
async def test_500_response_carries_trace_id():
    """Unhandled handler errors — the trace an operator most wants to
    look up from a user report — still answer with X-Trace-Id."""
    from cassmantle_tpu.engine.content import (
        FakeContentBackend,
        hash_embed,
        hash_similarity,
    )
    from cassmantle_tpu.engine.game import Game
    from cassmantle_tpu.engine.store import MemoryStore
    from cassmantle_tpu.server.app import create_app

    cfg = make_cfg()
    game = Game(cfg, MemoryStore(), FakeContentBackend(image_size=32),
                hash_embed, hash_similarity)

    async def boom(session):
        raise RuntimeError("handler bug")

    game.client_status = boom
    app = create_app(game, cfg, start_timer=False)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        res = await client.get("/client/status")
        assert res.status == 500
        trace_id = res.headers["X-Trace-Id"]
        spans = tracer.get_trace(trace_id)
        (root,) = [s for s in spans
                   if s["name"] == "http.get /client/status"]
        assert root["attrs"]["status"] == 500
    finally:
        await client.close()


# -- end-to-end acceptance -------------------------------------------------

async def _score_client():
    """HTTP -> engine -> REAL batching queue -> tiny MiniLM scorer:
    the fake content backend keeps round generation cheap while the
    guess path exercises the full traced queue + device stage."""
    from cassmantle_tpu.engine.content import FakeContentBackend
    from cassmantle_tpu.engine.game import Game
    from cassmantle_tpu.engine.store import MemoryStore
    from cassmantle_tpu.server.app import create_app
    from cassmantle_tpu.serving.service import InferenceService

    cfg = make_cfg()
    service = InferenceService(
        cfg, backend=FakeContentBackend(image_size=32))
    game = Game(cfg, MemoryStore(), service.content_backend,
                embed=service.embed, similarity=service.similarity,
                supervisor=service.supervisor)
    app = create_app(game, cfg, start_timer=False)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client, game


@pytest.mark.asyncio
async def test_trace_id_end_to_end_through_queue_and_device_stage():
    """Acceptance: a /compute_score response carries an X-Trace-Id whose
    trace contains queue-wait, batch-service, and a device-synchronized
    stage span — plus the X-Queue-Wait/X-Service-Time header pair."""
    client, game = await _score_client()
    try:
        await client.get("/init")
        current = await game.rounds.fetch_current_prompt()
        mask = current["masks"][0]
        res = await client.post(
            "/compute_score", json={"inputs": {str(mask): "storm"}})
        assert res.status == 200
        trace_id = res.headers["X-Trace-Id"]
        assert float(res.headers["X-Queue-Wait"]) >= 0.0
        assert float(res.headers["X-Service-Time"]) > 0.0

        dbg = await client.get(f"/debugz?trace={trace_id}")
        assert dbg.status == 200
        spans = (await dbg.json())["spans"]
        names = {s["name"] for s in spans}
        assert f"http.post /compute_score" in names
        assert "game.score" in names
        assert "score.queue_wait" in names
        assert "score.batch_service" in names
        # the device stage the batch ran, synchronized on its arrays
        stage = [s for s in spans if s["name"] == "scorer.encode_s"]
        assert stage and stage[0]["attrs"]["device_synced"] is True
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_metrics_content_negotiation():
    client, _ = await _score_client()
    try:
        res = await client.get("/metrics")
        data = await res.json()           # default stays JSON
        assert {"counters", "gauges", "timings"} <= set(data)
        res = await client.get("/metrics",
                               headers={"Accept": "text/plain"})
        assert res.status == 200
        assert "version=0.0.4" in res.headers["Content-Type"]
        text = await res.text()
        assert "# TYPE cassmantle_http_init_total counter" in text
        assert 'cassmantle_score_batch_seconds_bucket{le="+Inf"}' in text
        assert "cassmantle_score_batch_seconds_count" in text
        assert "# EOF" not in text       # plain Prometheus: no OM marks
        # OpenMetrics negotiation (ISSUE 18): counters declared on the
        # base name, mandatory # EOF terminator, exemplar-capable
        res = await client.get(
            "/metrics",
            headers={"Accept": "application/openmetrics-text"})
        assert res.status == 200
        assert "application/openmetrics-text" in \
            res.headers["Content-Type"]
        om = await res.text()
        assert om.endswith("# EOF\n")
        assert "# TYPE cassmantle_http_init counter" in om
        assert "cassmantle_http_init_total" in om
        # ?exemplars=1 adds the map WITHOUT touching the default keys
        res = await client.get("/metrics", params={"exemplars": "1"})
        data = await res.json()
        assert "exemplars" in data
        assert {"counters", "gauges", "timings"} <= set(data)
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_debugz_replays_trip_rotation_recovery_in_order():
    """Acceptance: /debugz replays breaker trip -> reserve rotation ->
    recovery causally; a degraded /readyz embeds the same tail."""
    from aiohttp.test_utils import TestClient as TC, TestServer as TS

    from cassmantle_tpu.engine.content import (
        FakeContentBackend,
        hash_embed,
        hash_similarity,
    )
    from cassmantle_tpu.engine.game import Game
    from cassmantle_tpu.engine.store import MemoryStore
    from cassmantle_tpu.server.app import create_app
    from cassmantle_tpu.utils.codec import encode_jpeg

    cfg = make_cfg()
    game = Game(cfg, MemoryStore(), FakeContentBackend(image_size=32),
                hash_embed, hash_similarity)
    app = create_app(game, cfg, start_timer=False)
    client = TC(TS(app))
    await client.start_server()
    try:
        watermark = flight_recorder.stats()["total_recorded"]
        # 1. trip the content breaker
        breaker = game.supervisor.content_breaker
        for _ in range(breaker.failure_threshold):
            breaker.record_failure()
        # 2. degraded /readyz carries the event history explaining it
        res = await client.get("/readyz")
        body = await res.json()
        assert res.status == 503
        assert any(e["kind"] == "breaker" and e["state"] == "open"
                   for e in body["events"])
        # 3. archive a reserve round, then promote with an empty buffer
        #    -> reserve rotation, not a replay
        state = json.dumps({"tokens": ["a", "fresh", "round"],
                            "masks": [1], "embeds": {}})
        jpeg = encode_jpeg(np.zeros((8, 8, 3), dtype=np.uint8))
        await game.reserve.archive("a fresh round", state, jpeg)
        await game.rounds.promote_buffer()
        # 4. recovery
        breaker.record_success()
        res = await client.get("/readyz")
        assert res.status == 200

        dbg = await client.get("/debugz")
        events = [e for e in (await dbg.json())["events"]
                  if e["seq"] > watermark]
        opened = next(i for i, e in enumerate(events)
                      if e["kind"] == "breaker" and e["state"] == "open")
        rotated = next(i for i, e in enumerate(events)
                       if e["kind"] == "round.reserve_promotion")
        closed = next(i for i, e in enumerate(events)
                      if e["kind"] == "breaker"
                      and e["state"] == "closed" and i > opened)
        assert opened < rotated < closed
        # filtered + trace-miss paths
        dbg = await client.get("/debugz?kind=breaker&n=5")
        assert all(e["kind"] == "breaker"
                   for e in (await dbg.json())["events"])
        missing = await client.get("/debugz?trace=deadbeef")
        assert missing.status == 404
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_round_generation_gets_background_trace():
    """Background round generation (no HTTP request) roots its own
    trace so pipeline stage spans have somewhere to land."""
    client, game = await _score_client()
    try:
        watermark = set(tracer.trace_ids())
        await game.rounds.buffer_contents()
        new = [t for t in tracer.trace_ids() if t not in watermark]
        gen_traces = [
            t for t in new
            if any(s["name"] == "round.generate"
                   for s in (tracer.get_trace(t) or []))]
        assert gen_traces, "round.generate root span not recorded"
    finally:
        await client.close()


# -- tail-based trace retention (ISSUE 18) ---------------------------------

def _root(tr, name, sleep_s=0.0, status="ok", mark=None):
    """One completed root trace; returns its trace id."""
    import time as _time

    try:
        with tr.span(name, root=True) as h:
            if mark is not None:
                tr.mark_retain(mark, h.ctx)
            if sleep_s:
                _time.sleep(sleep_s)
            if status != "ok":
                raise RuntimeError("boom")
    except RuntimeError:
        pass
    return h.trace_id


def test_tail_retains_slow_drops_healthy():
    """The acceptance bar: with the head floor at 0, a forced-slow
    request is retained while EVERY healthy same-route request drops —
    interesting-trace recall without head-sampling's storage cost."""
    from cassmantle_tpu.utils.logging import metrics

    tr = Tracer(capacity=64, sample_rate=0.0)
    tr.configure(tail_slow_default_s=0.05)
    retained_before = metrics.counter_total("obs.tail_retained")
    healthy = [_root(tr, "http.get /fetch") for _ in range(30)]
    slow = _root(tr, "http.get /fetch", sleep_s=0.08)
    assert all(tr.get_trace(t) is None for t in healthy)
    spans = tr.get_trace(slow)
    assert spans and spans[0]["status"] == "ok"
    assert metrics.counter_total("obs.tail_retained") == \
        retained_before + 1
    # verdicts reclaim pending occupancy either way
    assert tr.stats()["pending"] == 0


def test_tail_retains_errors_and_marks():
    tr = Tracer(capacity=8, sample_rate=0.0)
    tr.configure(tail_slow_default_s=10.0)
    errored = _root(tr, "http.post /x", status="error")
    assert tr.get_trace(errored)[0]["status"] == "error"
    # fast + ok but explicitly marked (shed/degraded/chaos/probe hook)
    marked = _root(tr, "http.post /x", mark="probe")
    assert tr.get_trace(marked) is not None
    # the per-route threshold overrides the default
    tr.configure(tail_slow_routes={"http.get /slowroute": 0.0})
    routed = _root(tr, "http.get /slowroute")
    assert tr.get_trace(routed) is not None


def test_tail_baseline_demotion():
    """The HTTP layer's routine-non-2xx verdict ("baseline": 307
    ownership hops, 4xx) demotes the error status — slow still
    retains, the status alone does not."""
    tr = Tracer(capacity=8, sample_rate=0.0)
    tr.configure(tail_slow_default_s=0.05)
    routine = _root(tr, "http.get /init", status="error",
                    mark="baseline")
    assert tr.get_trace(routine) is None
    slow = _root(tr, "http.get /init", sleep_s=0.08, mark="baseline")
    assert tr.get_trace(slow) is not None


def test_head_sampled_traces_stay_durable():
    """The healthy-baseline floor: a head-coin trace is durable
    immediately, never parked in pending."""
    tr = Tracer(capacity=8, sample_rate=1.0)
    tid = _root(tr, "http.get /fetch")
    assert tr.get_trace(tid) is not None
    assert tr.stats()["pending"] == 0


def test_pending_ttl_abandonment():
    """A pending trace whose root never completes (client disconnect,
    watchdog kill) ages out and its id is poisoned against torn
    revival."""
    import time as _time

    from cassmantle_tpu.utils.logging import metrics

    tr = Tracer(capacity=8, sample_rate=0.0)
    tr.configure(pending_ttl_s=0.0)
    abandoned_before = metrics.counter_total("obs.traces_abandoned")
    orphan = tr.new_root_ctx()
    assert not orphan.head
    tr.record_span("w.orphan", tr.child_ctx(orphan),
                   start_wall=_time.time(), duration_s=0.0)
    assert tr.stats()["pending"] == 1
    _time.sleep(0.002)
    # the next pending insert sweeps oldest-first
    other = tr.new_root_ctx()
    tr.record_span("w.other", tr.child_ctx(other),
                   start_wall=_time.time(), duration_s=0.0)
    assert metrics.counter_total("obs.traces_abandoned") == \
        abandoned_before + 1
    assert tr.get_trace(orphan.trace_id) is None
    tr.record_span("w.late", tr.child_ctx(orphan),
                   start_wall=_time.time(), duration_s=0.0)
    assert tr.get_trace(orphan.trace_id) is None


def test_no_tail_sampling_kill_switch_is_pre_tail_exact(monkeypatch):
    """CASSMANTLE_NO_TAIL_SAMPLING=1: the sampling coin IS the
    decision again — same rng stream, no pending buffer, no exemplar
    linkage. (Per-read: no restart needed.)"""
    import random as _random

    from cassmantle_tpu.obs.trace import _exemplar_probe

    monkeypatch.setenv("CASSMANTLE_NO_TAIL_SAMPLING", "1")
    tr = Tracer(capacity=32, sample_rate=0.5,
                rng=_random.Random(7))
    reference = _random.Random(7)
    for _ in range(32):
        ctx = tr.new_root_ctx()
        assert ctx.sampled == (reference.random() < 0.5)
        assert ctx.head    # nothing is ever deferred
    # sampled roots are durable immediately; unsampled record nothing;
    # the pending buffer never fills either way
    kept = [_root(tr, "http.get /fetch") for _ in range(16)]
    recorded = [t for t in kept if tr.get_trace(t) is not None]
    assert 0 < len(recorded) < 16
    assert tr.stats()["pending"] == 0
    with tr.span("http.get /x", root=True):
        assert _exemplar_probe() is None


def test_exemplars_follow_retention_verdict():
    """A histogram observation inside a pending trace parks as an
    exemplar candidate: retention promotes it into the bucket (visible
    in snapshot(exemplars=True) and the OpenMetrics exposition),
    a drop discards it — and the plain Prometheus exposition never
    shows exemplars at all."""
    from cassmantle_tpu.utils.logging import metrics

    rate, slow = tracer.sample_rate, tracer.tail_slow_default_s
    tracer.configure(sample_rate=0.0, tail_slow_default_s=10.0)
    try:
        with tracer.span("exms.root", root=True) as keep:
            metrics.observe("exms.kept_s", 0.004)
            tracer.mark_retain("probe", keep.ctx)
        with tracer.span("exms.root", root=True):
            metrics.observe("exms.dropped_s", 0.004)
        ex = metrics.snapshot(exemplars=True)["exemplars"]
        kept = {e["trace_id"] for e in ex["exms.kept_s"].values()}
        assert kept == {keep.trace_id}
        assert "exms.dropped_s" not in ex
        # a dropped trace's same-bucket observation must not clobber
        # the retained exemplar
        with tracer.span("exms.root", root=True):
            metrics.observe("exms.kept_s", 0.004)
        ex = metrics.snapshot(exemplars=True)["exemplars"]
        assert {e["trace_id"] for e in ex["exms.kept_s"].values()} == \
            {keep.trace_id}
        # default snapshot shape untouched (pinned backward-compatible)
        assert "exemplars" not in metrics.snapshot()
        om = metrics.openmetrics()
        assert om.endswith("# EOF\n")
        assert f'# {{trace_id="{keep.trace_id}"}}' in om
        prom = metrics.prometheus()
        assert "trace_id=" not in prom and "# EOF" not in prom
    finally:
        tracer.configure(sample_rate=rate, tail_slow_default_s=slow)
