"""Device cost & capacity observability (ISSUE 14): HBM telemetry with
explicit CPU degradation, compile wall-time recording, cache hit/miss
mirrors, roofline attribution on the warmed serving paths, the
`/readyz` device block, the `/debug/trace` gate, and the cost-model
drift gate. Fast tier (tests/conftest.py)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.obs import costmodel
from cassmantle_tpu.obs.device import DeviceMetrics
from cassmantle_tpu.utils import jit_sentinel
from cassmantle_tpu.utils.logging import Metrics, metrics


class _FakeDevice:
    def __init__(self, platform="tpu", dev_id=0, stats=None):
        self.platform = platform
        self.id = dev_id
        self._stats = stats

    def memory_stats(self):
        return self._stats


class _NoStatsDevice:
    """Old runtime: no memory_stats attribute at all."""

    platform = "tpu"
    id = 0


def _gauges(reg):
    return reg.snapshot()["gauges"]


# -- CPU-host degradation: explicit unavailable marker, never zeros --------

def test_memory_stats_none_marks_unavailable_not_zero():
    """A device whose memory_stats() returns None (the CPU backend)
    exports hbm_available=0 and NO hbm byte gauges at all — an all-zero
    worker would read as an empty chip and attract load."""
    reg = Metrics()
    dm = DeviceMetrics(registry=reg,
                       devices_fn=lambda: [_FakeDevice(stats=None)])
    seen = dm.sample()
    assert seen == {"tpu:0": None}
    gauges = _gauges(reg)
    assert gauges['device.hbm_available{device="tpu:0"}'] == 0.0
    assert not any(k.startswith("device.hbm_bytes") for k in gauges)
    assert not any(k.startswith("device.hbm_peak") for k in gauges)
    block = dm.device_block()
    assert block["devices"]["tpu:0"] == "unavailable"


def test_memory_stats_attribute_missing_marks_unavailable():
    reg = Metrics()
    dm = DeviceMetrics(registry=reg,
                       devices_fn=lambda: [_NoStatsDevice()])
    assert dm.sample() == {"tpu:0": None}
    assert _gauges(reg)['device.hbm_available{device="tpu:0"}'] == 0.0


def test_memory_stats_raising_marks_unavailable():
    class Raising(_FakeDevice):
        def memory_stats(self):
            raise RuntimeError("backend wedged")

    reg = Metrics()
    dm = DeviceMetrics(registry=reg,
                       devices_fn=lambda: [Raising()])
    assert dm.sample() == {"tpu:0": None}
    assert _gauges(reg)['device.hbm_available{device="tpu:0"}'] == 0.0


def test_sample_never_initializes_a_backend(monkeypatch):
    """A telemetry read must never be the thing that initializes a jax
    backend: --fake drill workers are accelerator-free, and on a TPU
    host an auxiliary worker would contend for the single-client
    runtime. With no backend initialized, sample() reports nothing."""
    from jax._src import xla_bridge

    reg = Metrics()
    dm = DeviceMetrics(registry=reg)
    monkeypatch.setattr(xla_bridge, "_backends", {})
    assert dm.sample() == {}
    assert not _gauges(reg)
    dm.note_dispatch("t2i")
    assert dm.highwater() == {}


def test_real_cpu_device_degrades_explicitly():
    """The ACTUAL CPU backend (tier-1's only device) must take the
    unavailable path end to end — jaxlib returns None there."""
    jax.local_devices()   # initialize the backend (the guard requires it)
    reg = Metrics()
    dm = DeviceMetrics(registry=reg)
    seen = dm.sample()
    assert seen, "no local devices visible"
    label = next(iter(seen))
    assert seen[label] is None
    assert _gauges(reg)[f'device.hbm_available{{device="{label}"}}'] == 0.0
    assert dm.device_block()["devices"][label] == "unavailable"


def test_hbm_stats_export_gauges():
    stats = {"bytes_in_use": 1_000, "bytes_limit": 16_000,
             "peak_bytes_in_use": 2_000}
    reg = Metrics()
    dm = DeviceMetrics(
        registry=reg,
        devices_fn=lambda: [_FakeDevice(dev_id=3, stats=stats)])
    dm.sample()
    gauges = _gauges(reg)
    assert gauges['device.hbm_bytes_in_use{device="tpu:3"}'] == 1_000
    assert gauges['device.hbm_bytes_limit{device="tpu:3"}'] == 16_000
    assert gauges['device.hbm_peak_bytes{device="tpu:3"}'] == 2_000
    assert gauges['device.hbm_available{device="tpu:3"}'] == 1.0
    block = dm.device_block()
    assert block["devices"]["tpu:3"] == {
        "bytes_in_use": 1_000, "bytes_limit": 16_000,
        "peak_bytes_in_use": 2_000}


def test_partial_stats_export_what_exists():
    reg = Metrics()
    dm = DeviceMetrics(
        registry=reg,
        devices_fn=lambda: [_FakeDevice(stats={"bytes_in_use": 7})])
    dm.sample()
    gauges = _gauges(reg)
    assert gauges['device.hbm_bytes_in_use{device="tpu:0"}'] == 7
    assert 'device.hbm_bytes_limit{device="tpu:0"}' not in gauges
    assert gauges['device.hbm_available{device="tpu:0"}'] == 1.0


def test_telemetry_going_dark_retracts_byte_gauges():
    """A device whose memory_stats starts failing MID-FLIGHT must not
    keep serving its last byte readings as current truth: the next
    sample flips hbm_available to 0 AND retracts the byte gauges (a
    frozen occupancy number would steer an autoscaler indefinitely)."""
    dev = _FakeDevice(stats={"bytes_in_use": 123, "bytes_limit": 456})
    reg = Metrics()
    dm = DeviceMetrics(registry=reg, devices_fn=lambda: [dev])
    dm.sample()
    assert _gauges(reg)['device.hbm_bytes_in_use{device="tpu:0"}'] == 123
    dev._stats = None                      # runtime hiccup: went dark
    dm.sample()
    gauges = _gauges(reg)
    assert gauges['device.hbm_available{device="tpu:0"}'] == 0.0
    assert not any(k.startswith("device.hbm_bytes") for k in gauges)
    assert dm.device_block()["devices"]["tpu:0"] == "unavailable"
    # ...and a recovered device re-exports
    dev._stats = {"bytes_in_use": 200}
    dm.sample()
    assert _gauges(reg)['device.hbm_bytes_in_use{device="tpu:0"}'] == 200


def test_highwater_tracks_max_per_pipeline():
    stats = {"bytes_in_use": 100}
    reg = Metrics()
    dm = DeviceMetrics(registry=reg,
                       devices_fn=lambda: [_FakeDevice(stats=stats)])
    dm.note_dispatch("t2i")
    stats["bytes_in_use"] = 500
    dm.note_dispatch("t2i")
    stats["bytes_in_use"] = 250   # lower sample must not regress the max
    dm.note_dispatch("t2i")
    dm.note_dispatch("prompt")
    assert dm.highwater() == {"t2i": 500, "prompt": 250}
    gauges = _gauges(reg)
    assert gauges['device.hbm_highwater_bytes{pipeline="t2i"}'] == 500
    assert gauges['device.hbm_highwater_bytes{pipeline="prompt"}'] == 250


def test_highwater_noop_without_telemetry():
    reg = Metrics()
    dm = DeviceMetrics(registry=reg,
                       devices_fn=lambda: [_FakeDevice(stats=None)])
    dm.note_dispatch("t2i")
    assert dm.highwater() == {}
    assert not any("highwater" in k for k in _gauges(reg))


# -- compile wall time (utils/jit_sentinel.py) ------------------------------

def _hist_total(name):
    totals = metrics.hist_totals(name)
    return totals[2] if totals else 0


def test_compile_wall_time_recorded_then_quiet():
    """A fresh compile lands a jit.compile_s observation, bumps the
    cumulative jit.compile_seconds counter, and names the function in
    the snapshot; a warmed steady-state call records NOTHING (the
    acceptance bar: at least one observation during warmup, zero
    after). The autouse fixture armed + reset the sentinel."""
    assert jit_sentinel.sentinel_active()

    def obs_device_warmup_fn(x):
        return x * 3 + 1

    fn = jax.jit(obs_device_warmup_fn)
    before_hist = _hist_total("jit.compile_s")
    before_counter = metrics.counter_total("jit.compile_seconds")
    fn(jnp.ones((8,))).block_until_ready()      # warmup: compiles
    after_warmup = _hist_total("jit.compile_s")
    assert after_warmup > before_hist
    assert metrics.counter_total("jit.compile_seconds") > before_counter
    snap = jit_sentinel.compile_time_snapshot()
    assert snap.get("obs_device_warmup_fn", 0) > 0
    # steady state: same shapes, warmed cache — zero new observations
    fn(jnp.ones((8,))).block_until_ready()
    assert _hist_total("jit.compile_s") == after_warmup


def test_compile_time_parser_handles_finished_record():
    from cassmantle_tpu.utils.jit_sentinel import (
        _parse_finished,
        compile_time_snapshot,
        reset_counts,
    )

    reset_counts()
    _parse_finished(
        "Finished XLA compilation of jit(my_fn) in 2.5 sec")
    assert compile_time_snapshot() == {"my_fn": 2.5}
    # malformed tails must be ignored, never raise
    _parse_finished("Finished XLA compilation of jit(x) in soon")
    _parse_finished("Finished XLA compilation of nonsense")
    assert compile_time_snapshot() == {"my_fn": 2.5}
    reset_counts()


def test_slow_compile_lands_in_flight_recorder():
    """Compiles >= 1 s land in /debugz (kind jit.compile); sub-second
    warmup bursts stay metric-only so they can't flush the event ring
    of the supervision story."""
    from cassmantle_tpu.obs.recorder import flight_recorder
    from cassmantle_tpu.utils.jit_sentinel import _record_compile_time

    _record_compile_time("jit(tiny_fn)", 0.01)
    _record_compile_time("jit(sdxl_sample)", 97.2)
    events = flight_recorder.tail(50, kind="jit.compile")
    fns = [e["fn"] for e in events]
    assert "sdxl_sample" in fns
    assert "tiny_fn" not in fns
    jit_sentinel.reset_counts()


# -- persistent-compile-cache hit/miss mirrors ------------------------------

def test_cache_event_listener_mirrors_gauges():
    from cassmantle_tpu.utils import compile_cache

    compile_cache._arm_cache_listener()
    before = compile_cache.cache_event_counts()
    # drive jax.monitoring's real listener fan-out, no compile needed
    from jax import monitoring

    monitoring.record_event("/jax/compilation_cache/cache_misses")
    monitoring.record_event("/jax/compilation_cache/cache_hits")
    monitoring.record_event("/jax/compilation_cache/cache_hits")
    after = compile_cache.cache_event_counts()
    assert after["misses"] == before["misses"] + 1
    assert after["hits"] == before["hits"] + 2
    gauges = metrics.snapshot()["gauges"]
    assert gauges["jit.cache_hits"] == after["hits"]
    assert gauges["jit.cache_misses"] == after["misses"]


# -- roofline attribution: the warmed serving smoke (acceptance) ------------

@pytest.fixture(scope="module")
def tiny_cfg():
    return _tiny_config()


def _pipeline_gauge(name, pipeline):
    return metrics.snapshot()["gauges"].get(
        f'{name}{{pipeline="{pipeline}"}}')


def _spans_named(trace_id, name):
    from cassmantle_tpu.obs.trace import tracer

    return [s for s in (tracer.get_trace(trace_id) or [])
            if s["name"] == name]


def test_t2i_dispatch_carries_flops_and_mxu(tiny_cfg):
    """The acceptance smoke, image path: a warmed generate produces a
    stage span carrying flops_est attrs, a nonzero
    pipeline.mxu_utilization{pipeline=t2i} gauge, and a
    request.device_flops delta — and the warmed dispatch records zero
    jit.compile_s observations (sentinel still zero-recompile)."""
    from cassmantle_tpu.obs.trace import tracer
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    pipe = Text2ImagePipeline(tiny_cfg)
    pipe.generate(["warmup"], seed=1)           # compiles
    compile_obs = _hist_total("jit.compile_s")
    flops_before = metrics.counter_total("request.device_flops")
    with tracer.span("test.t2i", root=True) as span:
        with jit_sentinel.no_new_compiles():
            pipe.generate(["a storm over the harbor"], seed=2)
    stage = _spans_named(span.trace_id, "pipeline.t2i_s")
    assert stage, "no device stage span recorded"
    assert stage[-1]["attrs"]["flops_est"] > 0
    assert stage[-1]["attrs"]["mxu_utilization"] > 0
    assert metrics.counter_total("request.device_flops") > flops_before
    mxu = _pipeline_gauge("pipeline.mxu_utilization", "t2i")
    assert mxu is not None and mxu > 0
    # warmup observed compile_s at least once; warmed dispatch: zero
    assert compile_obs > 0
    assert _hist_total("jit.compile_s") == compile_obs


def test_t2i_flops_estimate_matches_analytic_trace(tiny_cfg):
    """The per-dispatch estimate equals a direct trace of the pipeline
    impl (the committed artifact never matches the test config, so the
    trace-once fallback is the path under test)."""
    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    pipe = Text2ImagePipeline(tiny_cfg)
    per_image = pipe._dispatch_flops(pipe._sample, tiny_cfg.sampler)
    ids = jax.ShapeDtypeStruct((1, pipe.pad_len), jnp.int32)
    expect, _ = costmodel.trace_cost(
        pipe._sample_impl, pipe._params, ids, ids, jax.random.PRNGKey(0))
    assert per_image == pytest.approx(expect, rel=1e-6)
    # cached: second resolution returns the same object fast
    assert pipe._dispatch_flops(pipe._sample, tiny_cfg.sampler) \
        == per_image


def test_failed_dispatch_attributes_no_flops():
    """A dispatch that raises did not do its analytic FLOPs: no
    request.device_flops, no mxu gauge spike from a short
    elapsed-at-failure (attribution is success-gated)."""
    from cassmantle_tpu.utils.profiling import block_timer

    before = metrics.counter_total("request.device_flops")
    with pytest.raises(RuntimeError):
        with block_timer("pipeline.t2i_s", flops_est=1e18,
                         pipeline="t2i"):
            raise RuntimeError("chaos: device OOM mid-dispatch")
    assert metrics.counter_total("request.device_flops") == before


def test_tier_variant_flops_resolve_in_background(tiny_cfg):
    """A brownout-tier variant engages exactly when the system sheds
    latency: its cost trace must run off-thread — first resolutions
    answer None (no attribution), the cached figure appears shortly."""
    import dataclasses
    import time

    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    pipe = Text2ImagePipeline(tiny_cfg)
    scfg = dataclasses.replace(tiny_cfg.sampler, num_steps=2)
    assert pipe._dispatch_flops(pipe._sample, scfg) is None
    got = None
    deadline = time.time() + 30
    while time.time() < deadline:
        got = pipe._dispatch_flops(pipe._sample, scfg)
        if got is not None:
            break
        time.sleep(0.05)
    assert got is not None and got > 0


def test_prompt_dispatch_carries_flops(tiny_cfg):
    from cassmantle_tpu.obs.trace import tracer
    from cassmantle_tpu.serving.pipeline import PromptGenerator

    gen = PromptGenerator(tiny_cfg)
    gen.generate_batch(["warm"])                # compiles
    with tracer.span("test.prompt", root=True) as span:
        gen.generate_batch(["the tide rose", "a lantern flickered"])
    stage = _spans_named(span.trace_id, "pipeline.prompt_s")
    assert stage and stage[-1]["attrs"]["flops_est"] > 0
    # 2N flops/token × dispatched tokens (buckets are shape-exact)
    n = costmodel.params_count(gen.params)
    assert gen._token_flops() == pytest.approx(2.0 * n)
    mxu = _pipeline_gauge("pipeline.mxu_utilization", "prompt")
    assert mxu is not None and mxu > 0


def test_scorer_dispatch_carries_flops(tiny_cfg):
    from cassmantle_tpu.obs.trace import tracer
    from cassmantle_tpu.ops.scorer import EmbeddingScorer

    scorer = EmbeddingScorer(tiny_cfg.models.minilm, seq_len=8,
                             batch_buckets=(4,))
    scorer.embed(["warm"])                      # compiles
    with tracer.span("test.scorer", root=True) as span:
        scorer.embed(["storm", "harbor"])
    stage = _spans_named(span.trace_id, "scorer.encode_s")
    assert stage and stage[-1]["attrs"]["flops_est"] > 0
    mxu = _pipeline_gauge("pipeline.mxu_utilization", "scorer")
    assert mxu is not None and mxu > 0


def test_committed_cost_model_resolves_without_tracing():
    """A signature match against the committed artifact short-circuits
    the trace (production configs pay zero startup tracing)."""
    model = costmodel.load_cost_model()
    entry = model["pipelines"]["t2i"]
    calls = []

    def tracer_fn():
        calls.append(1)
        return 1.0

    costmodel.reset_runtime_cache()
    try:
        got = costmodel.flops_per_item("t2i", entry["signature"],
                                       tracer=tracer_fn)
        assert got == entry["flops_per_item"]
        assert not calls
        # mismatched signature falls to the tracer, cached once
        got2 = costmodel.flops_per_item("t2i", "no-such-sig",
                                        tracer=tracer_fn)
        assert got2 == 1.0 and calls == [1]
        costmodel.flops_per_item("t2i", "no-such-sig", tracer=tracer_fn)
        assert calls == [1]
    finally:
        costmodel.reset_runtime_cache()


def test_failing_tracer_degrades_to_none():
    costmodel.reset_runtime_cache()
    try:
        def boom():
            raise RuntimeError("trace failed")

        assert costmodel.flops_per_item("t2i", "sig-x",
                                        tracer=boom) is None
        # and the failure is cached — not retried per dispatch
        assert costmodel.flops_per_item("t2i", "sig-x") is None
    finally:
        costmodel.reset_runtime_cache()


# -- /readyz device block + /debug/trace gate -------------------------------

async def _make_client(cfg):
    import dataclasses

    from aiohttp.test_utils import TestClient, TestServer

    from cassmantle_tpu.engine.content import (
        FakeContentBackend,
        hash_embed,
        hash_similarity,
    )
    from cassmantle_tpu.engine.game import Game
    from cassmantle_tpu.engine.store import MemoryStore
    from cassmantle_tpu.server.app import create_app

    cfg = cfg.replace(game=dataclasses.replace(
        cfg.game, rate_limit_default=1000.0, rate_limit_api=1000.0))
    game = Game(cfg, MemoryStore(), FakeContentBackend(image_size=32),
                hash_embed, hash_similarity)
    app = create_app(game, cfg, start_timer=False)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


@pytest.mark.asyncio
async def test_readyz_embeds_device_telemetry(tiny_cfg):
    jax.local_devices()   # serving processes have a backend up; so do we
    client = await _make_client(tiny_cfg)
    try:
        res = await client.get("/readyz")
        body = await res.json()
        block = body["device_telemetry"]
        # CPU host: every device explicitly unavailable, never zeros
        assert block["devices"]
        assert all(v == "unavailable" for v in block["devices"].values())
        assert "hbm_highwater_bytes" in block
        compile_block = block["compile"]
        assert {"functions", "compiles", "total_s",
                "slowest"} <= set(compile_block)
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_metrics_scrape_refreshes_device_gauges(tiny_cfg):
    jax.local_devices()
    client = await _make_client(tiny_cfg)
    try:
        res = await client.get("/metrics")
        gauges = (await res.json())["gauges"]
        avail = [v for k, v in gauges.items()
                 if k.startswith("device.hbm_available")]
        assert avail and all(v == 0.0 for v in avail)  # CPU backend
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_debug_trace_gated_like_debugz(tiny_cfg, monkeypatch):
    """Loopback passes (status quo); a non-loopback caller needs the
    cluster token (the /debugz gate, ISSUE 14) — and a successful
    capture counts obs.profiler_captures."""
    from cassmantle_tpu.server import app as app_mod

    client = await _make_client(tiny_cfg)
    try:
        before = metrics.counter_total("obs.profiler_captures")
        res = await client.post("/debug/trace?seconds=0.05&name=gate")
        assert res.status == 200
        assert metrics.counter_total("obs.profiler_captures") \
            == before + 1
        # sever the loopback leg: now only the cluster token admits
        monkeypatch.setattr(app_mod, "_is_loopback", lambda req: False)
        res = await client.post("/debug/trace?seconds=0.05&name=gate")
        assert res.status == 403
        fabric = client.app[app_mod._FABRIC]
        # the legacy one-Game wrap runs heartbeatless and never minted
        # a key; mint one the way the first fabric beat would — the
        # GATE (not key distribution, covered in test_obs_cluster) is
        # what this test pins
        await fabric._ensure_cluster_key()
        token = fabric.cluster_token()
        assert token, "fabric should mint a cluster token"
        res = await client.post(
            "/debug/trace?seconds=0.05&name=gate",
            headers={"X-Cluster-Auth": token})
        assert res.status == 200
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_debug_trace_single_flight(tiny_cfg):
    import asyncio

    client = await _make_client(tiny_cfg)
    try:
        first = asyncio.create_task(
            client.post("/debug/trace?seconds=0.4&name=sf"))
        await asyncio.sleep(0.1)   # let the first capture start
        second = await client.post("/debug/trace?seconds=0.1&name=sf")
        assert second.status == 409
        assert (await first).status == 200
    finally:
        await client.close()


# -- cost-model drift gate (satellite: CI/tooling) --------------------------

def test_cost_model_artifact_matches_regeneration(tmp_path):
    """Regenerate data/cost_model.json via --emit-cost-model (in
    process — pure eval_shape tracing, no weights) and compare to the
    committed artifact: a model/config change that shifts the analytic
    cost MUST re-emit the artifact in the same PR (the fault-point/
    env-flag lint spirit, applied to the cost model)."""
    from tools.profile_unet import emit_cost_model

    out = tmp_path / "cost_model.json"
    regenerated = emit_cost_model(str(out))
    committed_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "data", "cost_model.json")
    with open(committed_path) as f:
        committed = json.load(f)
    assert regenerated == committed, (
        "data/cost_model.json drifted from the configs: rerun "
        "`python tools/profile_unet.py --platform cpu "
        "--emit-cost-model data/cost_model.json` and commit the result")


def test_trace_cost_counts_scan_trip_and_bytes():
    """trace_cost multiplies scan bodies by their trip count and the
    byte proxy counts operand+result buffers."""
    def body(c, _):
        return c @ c, None

    def scanned(x):
        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    x = jnp.ones((8, 8), jnp.float32)
    flops, hbm = costmodel.trace_cost(scanned, x)
    assert flops == pytest.approx(5 * 2 * 8 * 8 * 8)
    # per matmul: 2 operands + 1 result, 8x8 f32 each
    assert hbm == pytest.approx(5 * 3 * 8 * 8 * 4)


def test_params_count_and_bytes():
    tree = {"a": np.zeros((4, 4), np.float32),
            "b": {"c": np.zeros((10,), np.int8)}}
    assert costmodel.params_count(tree) == 26
    assert costmodel.params_bytes(tree) == 4 * 4 * 4 + 10
