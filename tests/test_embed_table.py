"""Zero-device guess scoring (ISSUE 16): the committed int8 wordlist
embedding table and the table -> LRU -> device scoring ladder.

Layers covered here:

- quantization fidelity: int8-vs-fp32 cosine parity pinned across the
  FULL wordlist (tiny test encoder — the quantizer under test is
  config-independent) and rank preservation on the pos_gold content
  words;
- artifact discipline: the committed data/embed_table.bin is
  signature-gated against what tools/build_embed_table.py would
  regenerate (the same drift contract as the cost-model artifact), and
  its structure (row count, unit lookups, mmap int8 rows) is pinned;
- the ladder: key normalization + OOV/empty/unicode fallbacks, the
  scorer's table rung counters, answer pinning at promotion
  (RoundManager._notify_answers -> pin_answers), and the --fake
  worker's TableFirstSimilarity wrapper;
- the acceptance bar: a fully in-vocabulary guess completes through
  InferenceService.similarity with ZERO device dispatch and ZERO queue
  submits (score.batches/score.items flat while scorer.table_hits and
  overload.table_served advance), and CASSMANTLE_NO_EMBED_TABLE=1
  reverts to the queued path bit-exactly.
"""

import asyncio
import json

import numpy as np
import pytest

from cassmantle_tpu.config import test_config as tiny_config
from cassmantle_tpu.ops.embed_table import (
    EMBED_TABLE_PATH,
    EmbedTable,
    TableFirstSimilarity,
    build_fake_table,
    normalize_key,
    pin_answers_hash,
    quantize_rows,
    read_header,
)
from cassmantle_tpu.server.assets import load_wordlist
from cassmantle_tpu.utils.logging import metrics


@pytest.fixture(scope="module")
def wordlist():
    return [normalize_key(w) for w in load_wordlist()]


@pytest.fixture(scope="module")
def tiny_scorer():
    from cassmantle_tpu.ops.scorer import EmbeddingScorer

    cfg = tiny_config()
    # table=False: the fidelity fixtures need the raw fp32 encoder, not
    # whatever artifact happens to be committed
    return EmbeddingScorer(cfg.models.minilm, seq_len=8,
                           batch_buckets=(512,), embed_cache_size=0,
                           table=False)


@pytest.fixture(scope="module")
def full_emb(tiny_scorer, wordlist):
    """fp32 embeddings of the ENTIRE wordlist through the tiny encoder
    (~25 s once per module): the quantization-parity acceptance bar is
    'across the full wordlist', not a sample."""
    return np.asarray(tiny_scorer.embed(wordlist), dtype=np.float32)


def _unit(rows: np.ndarray) -> np.ndarray:
    return rows / np.maximum(
        np.linalg.norm(rows, axis=-1, keepdims=True), 1e-8)


def test_int8_cosine_parity_full_wordlist(wordlist, full_emb):
    """The tentpole's fidelity bound: for every wordlist row, the int8
    lookup cosine against a spread of probe words stays within 1e-2 of
    the fp32 cosine (measured 4.8e-3 max / ~2e-4 mean at dim 64 over
    ~370k pairs; production dim 384 quantizes finer), and the fused
    score_pairs() int32-dot path agrees with the lookup path to float
    associativity."""
    table = EmbedTable.from_embeddings(wordlist, full_emb)
    assert len(table) == len(wordlist)

    fp32 = _unit(full_emb)
    q8 = np.stack([table.lookup(w) for w in
                   wordlist[:: max(1, len(wordlist) // 4096)]])
    # lookups come out unit-norm (scale cancels; norms stored over q)
    assert np.allclose(np.linalg.norm(q8, axis=-1), 1.0, atol=1e-5)

    probes = wordlist[:: max(1, len(wordlist) // 64)][:64]
    p_fp = fp32[[wordlist.index(p) for p in probes[:8]]]
    p_q8 = np.stack([table.lookup(p) for p in probes[:8]])
    # full-vocab x probe cosine error, fp32 vs int8 lookup path
    int8_all = np.stack([table.lookup(w) for w in wordlist])
    err = np.abs(fp32 @ p_fp.T - int8_all @ p_q8.T)
    assert float(err.max()) < 1e-2, \
        f"int8 cosine error {err.max():.2e} exceeds the 1e-2 pin"
    assert float(err.mean()) < 1e-3, \
        f"int8 mean cosine error {err.mean():.2e} exceeds the 1e-3 pin"

    # fused int32-dot scoring == lookup-dot scoring (same stored norms)
    pairs = [(probes[i], probes[(i + 3) % len(probes)])
             for i in range(len(probes))]
    fused, served = table.score_pairs(pairs)
    assert served.all()
    by_lookup = np.asarray([
        float(np.dot(table.lookup(a), table.lookup(b)))
        for a, b in pairs], dtype=np.float32)
    assert np.allclose(fused, by_lookup, atol=1e-6)


def test_rank_preservation_pos_gold(wordlist, full_emb):
    """Scoring is only consumed as an ordering (closest guess wins the
    round): for pos_gold content words present in the wordlist, any
    candidate pair whose fp32 cosines differ by more than 2e-2 (well
    clear of the ~5e-3 max quantization error at this dim) must keep
    its relative order under int8 scoring."""
    import os

    table = EmbedTable.from_embeddings(wordlist, full_emb)
    fp32 = _unit(full_emb)
    index = {w: i for i, w in enumerate(wordlist)}

    gold = os.path.join(os.path.dirname(EMBED_TABLE_PATH),
                        "pos_gold.txt")
    cands = []
    with open(gold) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            for tok in line.split():
                w = normalize_key(tok.rsplit("/", 1)[0])
                if w in index and w not in cands:
                    cands.append(w)
    assert len(cands) >= 40, f"pos_gold yielded only {len(cands)} words"

    anchors = cands[:8]
    others = cands[8:]
    flips = []
    for a in anchors:
        fp_scores = fp32[[index[o] for o in others]] @ fp32[index[a]]
        q_scores, served = table.score_pairs([(o, a) for o in others])
        assert served.all()
        order = np.argsort(-fp_scores)
        for r1, r2 in zip(order, order[1:]):
            if fp_scores[r1] - fp_scores[r2] > 2e-2 \
                    and q_scores[r1] <= q_scores[r2]:
                flips.append((a, others[r1], others[r2]))
    assert not flips, f"int8 flipped well-separated ranks: {flips[:5]}"


def test_committed_artifact_drift_gate():
    """Tier-1 drift gate: the committed data/embed_table.bin signature
    must match what tools/build_embed_table.py would stamp for the
    current wordlist + scorer config + weights identity."""
    from tools.build_embed_table import expected_signature

    header = read_header(EMBED_TABLE_PATH)
    expect = expected_signature()
    assert header["signature"] == expect, (
        f"data/embed_table.bin signature {header['signature']} != "
        f"expected {expect} — the wordlist, scorer config, or weights "
        f"changed; rebuild with `python -m cassmantle_tpu "
        f"build-embed-table --emit` and commit the artifact")


def test_committed_artifact_structure():
    """The committed artifact loads with its own signature, covers the
    full wordlist, memory-maps int8 rows, and serves unit-norm lookups
    + self-cosine 1.0 scores."""
    header = read_header(EMBED_TABLE_PATH)
    table = EmbedTable.load(EMBED_TABLE_PATH,
                            expected_signature=header["signature"])
    assert table is not None
    words = [normalize_key(w) for w in load_wordlist()]
    assert len(table) == len(words)
    assert header["dim"] == 384 and header["version"] == 1
    assert table._rows.dtype == np.int8
    assert isinstance(table._rows, np.memmap)

    probe = words[0]
    vec = table.lookup(probe)
    assert vec is not None and vec.dtype == np.float32
    assert abs(float(np.linalg.norm(vec)) - 1.0) < 1e-5
    scores, served = table.score_pairs([(probe, probe)])
    assert served.all() and abs(float(scores[0]) - 1.0) < 1e-3
    # a mismatched signature must refuse to arm (warning path)
    assert EmbedTable.load(EMBED_TABLE_PATH,
                           expected_signature="bogus") is None


def test_lookup_normalization_and_fallbacks():
    """Key discipline: NFKC + casefold + strip, so client-typed unicode
    variants hit the same row; OOV / empty lookups return None and
    partially-OOV pairs come back unserved with score 0."""
    words = ["café", "straße", "apple"]
    rng = np.random.default_rng(3)
    emb = rng.normal(size=(3, 16)).astype(np.float32)
    table = EmbedTable.from_embeddings(words, emb)
    # NFKC composes the combining accent; casefold folds case + ß
    assert table.lookup("CAFÉ ") is not None
    assert table.lookup("STRASSE") is not None
    assert table.lookup(" Apple\n") is not None
    assert table.lookup("") is None
    assert table.lookup("   ") is None
    assert table.lookup("zz-not-in-vocab") is None

    scores, served = table.score_pairs(
        [("apple", "zz-not-in-vocab"), ("apple", "café")])
    assert not served[0] and scores[0] == 0.0
    assert served[1]
    empty_scores, empty_served = table.score_pairs([])
    assert len(empty_scores) == 0 and len(empty_served) == 0


def test_scorer_table_rung_counters(tiny_scorer, wordlist, full_emb):
    """EmbeddingScorer.embed ladder accounting: in-table texts are
    served from rung 0 (scorer.table_hits; rows bit-identical to
    table.lookup), misses fall through and count scorer.table_oov, and
    the fall-through rows still populate/hit the LRU on repeat."""
    from cassmantle_tpu.ops.scorer import EmbeddingScorer

    cfg = tiny_config()
    table = EmbedTable.from_embeddings(wordlist[:1024],
                                       full_emb[:1024])
    scorer = EmbeddingScorer(cfg.models.minilm, seq_len=8,
                             batch_buckets=(4, 16), table=table)
    invocab = wordlist[:3]
    oov = ["zzqx-one", "zzqx-two"]
    before = {k: metrics.counter_total(k) for k in
              ("scorer.table_hits", "scorer.table_oov",
               "scorer.embed_cache_hits")}
    rows = scorer.embed(invocab + oov)
    assert metrics.counter_total("scorer.table_hits") \
        == before["scorer.table_hits"] + 3
    assert metrics.counter_total("scorer.table_oov") \
        == before["scorer.table_oov"] + 2
    for i, w in enumerate(invocab):
        assert np.array_equal(rows[i], table.lookup(w))
    # repeat: the two OOV rows now come from the LRU rung
    scorer.embed(oov)
    assert metrics.counter_total("scorer.embed_cache_hits") \
        == before["scorer.embed_cache_hits"] + 2


def test_scorer_pin_answers(tiny_scorer, wordlist, full_emb):
    """pin_answers embeds only rows the table lacks, pins them through
    the identical quantizer, dedups, and is idempotent — the promotion
    hook must be free when answers are already in vocabulary."""
    from cassmantle_tpu.ops.scorer import EmbeddingScorer

    cfg = tiny_config()
    table = EmbedTable.from_embeddings(wordlist[:128], full_emb[:128])
    scorer = EmbeddingScorer(cfg.models.minilm, seq_len=8,
                             batch_buckets=(4, 16), table=table)
    assert scorer.pin_answers([wordlist[0], wordlist[1]]) == 0
    pinned = scorer.pin_answers(["Unseen-Answer", "unseen-answer",
                                 wordlist[2]])
    assert pinned == 1
    assert table.contains("unseen-answer")
    assert scorer.pin_answers(["unseen-answer"]) == 0
    scores, served = table.score_pairs(
        [(wordlist[0], "unseen-answer")])
    assert served.all()
    # the pinned row rides the same quantizer as committed rows: its
    # lookup is unit-norm and self-cosine is 1.0
    vec = table.lookup("unseen-answer")
    assert abs(float(np.linalg.norm(vec)) - 1.0) < 1e-5


def _service_with_table():
    from cassmantle_tpu.serving.service import InferenceService

    svc = InferenceService(tiny_config())
    words = ["alpha", "beta", "gamma"]
    emb = np.asarray(svc.scorer.embed(words), dtype=np.float32)
    svc.scorer.table = EmbedTable.from_embeddings(words, emb)
    return svc, words


def test_service_zero_device_zero_queue(monkeypatch):
    """THE acceptance pin: a fully in-vocabulary pair through
    InferenceService.similarity touches neither the batching queue nor
    the device — score.batches / score.items / scorer.embed_cache_misses
    stay flat while scorer.table_hits advances by 2 and
    overload.table_served by 1. The queue is deliberately NOT started:
    any submit would hang the test, so passing IS the bypass proof."""
    monkeypatch.delenv("CASSMANTLE_NO_EMBED_TABLE", raising=False)
    svc, words = _service_with_table()
    flat = ("score.batches", "score.items",
            "scorer.embed_cache_misses")
    moving = ("scorer.table_hits", "overload.table_served")
    before = {k: metrics.counter_total(k) for k in flat + moving}

    scores = asyncio.run(
        asyncio.wait_for(svc.similarity([("alpha", "beta")]), 5.0))
    assert scores.shape == (1,) and scores.dtype == np.float32

    for k in flat:
        assert metrics.counter_total(k) == before[k], \
            f"{k} moved — the table fast path dispatched device work"
    assert metrics.counter_total("scorer.table_hits") \
        == before["scorer.table_hits"] + 2
    assert metrics.counter_total("overload.table_served") \
        == before["overload.table_served"] + 1


def test_service_partial_pair_merges_queue_scores(monkeypatch):
    """A batch mixing in-vocab and OOV pairs serves the covered pairs
    from the table and routes ONLY the rest through the queue, merging
    scores back in request order."""
    monkeypatch.delenv("CASSMANTLE_NO_EMBED_TABLE", raising=False)
    svc, words = _service_with_table()
    pairs = [("alpha", "beta"), ("alpha", "zz-oov-word"),
             ("beta", "gamma")]

    async def run():
        svc.score_queue.start()
        got = await svc.similarity(pairs)
        await svc.stop()
        return got

    before = metrics.counter_total("score.items")
    scores = asyncio.run(run())
    # only the OOV pair rode the queue
    assert metrics.counter_total("score.items") == before + 1
    direct = svc.scorer.similarity(pairs)
    table_scores, served = svc.scorer.table.score_pairs(pairs)
    assert served[0] and not served[1] and served[2]
    assert scores[0] == pytest.approx(table_scores[0])
    assert scores[2] == pytest.approx(table_scores[2])
    assert scores[1] == pytest.approx(direct[1], abs=1e-6)


def test_kill_switch_reverts_bit_exact(monkeypatch):
    """CASSMANTLE_NO_EMBED_TABLE=1 must reproduce the pre-table queued
    path BIT-exactly (same fp32 encoder, same queue), not merely
    approximately — the operator's revert story is 'flip the flag,
    get yesterday's numbers'."""
    svc, words = _service_with_table()
    pairs = [("alpha", "beta"), ("beta", "gamma")]

    monkeypatch.setenv("CASSMANTLE_NO_EMBED_TABLE", "1")

    async def run():
        svc.score_queue.start()
        got = await svc.similarity(pairs)
        await svc.stop()
        return got

    before_hits = metrics.counter_total("scorer.table_hits")
    killed = asyncio.run(run())
    assert metrics.counter_total("scorer.table_hits") == before_hits
    reference = np.asarray(svc.scorer.similarity(pairs),
                           dtype=np.float32)
    assert np.array_equal(killed, reference), \
        "kill switch did not revert to the queued fp32 path bit-exactly"
    # and the switch really changes the serving rung: armed scores are
    # the quantized table's, close to fp32 but not the same code path
    monkeypatch.delenv("CASSMANTLE_NO_EMBED_TABLE")
    armed = asyncio.run(
        asyncio.wait_for(svc.similarity(pairs), 5.0))
    assert np.allclose(armed, reference, atol=5e-3)


def test_round_promotion_pins_answers():
    """RoundManager._notify_answers extracts the masked answer tokens
    from a promoted prompt_state (dict, bytes, or JSON str — the three
    shapes the call sites hold) and hands them to the pin hook off the
    event loop; a failing hook counts rounds.answer_pin_failures and
    never breaks promotion."""
    from cassmantle_tpu.engine.rounds import RoundManager

    rm = RoundManager.__new__(RoundManager)
    rm.metric_labels = {}
    pinned = []
    rm.on_answers = pinned.extend

    state = {"tokens": ["a", "stormy", "sea", "at", "dusk"],
             "masks": [1, 4]}
    asyncio.run(rm._notify_answers(state))
    asyncio.run(rm._notify_answers(json.dumps(state).encode()))
    asyncio.run(rm._notify_answers(json.dumps(state)))
    assert pinned == ["stormy", "dusk"] * 3

    def boom(_words):
        raise RuntimeError("pin exploded")

    rm.on_answers = boom
    before = metrics.counter_total("rounds.answer_pin_failures")
    asyncio.run(rm._notify_answers(state))   # must not raise
    assert metrics.counter_total("rounds.answer_pin_failures") \
        == before + 1
    # a None hook (real-path services absent) is a silent no-op
    rm.on_answers = None
    asyncio.run(rm._notify_answers(state))


def test_table_first_similarity_fake_path(monkeypatch):
    """The --fake worker ladder (TableFirstSimilarity): covered pairs
    never reach the fallback, mixed batches split-and-merge, the kill
    switch routes everything through, and pin_answers_hash makes OOV
    template answers servable."""
    monkeypatch.delenv("CASSMANTLE_NO_EMBED_TABLE", raising=False)
    monkeypatch.setenv("CASSMANTLE_FAKE_EMBED_TABLE", "1")
    table = build_fake_table()
    assert len(table) == len(load_wordlist())

    calls = []

    async def fallback(pairs):
        calls.append(list(pairs))
        return np.full((len(pairs),), 0.25, dtype=np.float32)

    ladder = TableFirstSimilarity(table, fallback)
    w = [normalize_key(x) for x in load_wordlist()[:3]]

    before = metrics.counter_total("overload.table_served")
    scores = asyncio.run(ladder([(w[0], w[1]), (w[1], w[2])]))
    assert not calls, "fully covered pairs leaked to the fallback"
    assert metrics.counter_total("overload.table_served") == before + 2

    mixed = asyncio.run(ladder([(w[0], w[1]), (w[0], "zz-oov")]))
    assert calls == [[(w[0], "zz-oov")]]
    assert mixed[1] == pytest.approx(0.25)

    monkeypatch.setenv("CASSMANTLE_NO_EMBED_TABLE", "1")
    calls.clear()
    asyncio.run(ladder([(w[0], w[1])]))
    assert calls == [[(w[0], w[1])]]
    monkeypatch.delenv("CASSMANTLE_NO_EMBED_TABLE")

    # fake promotion pin: template answers outside the wordlist (e.g.
    # 'crooked') become servable through the hash embedder
    assert not table.contains("crooked")
    assert pin_answers_hash(table, ["Crooked", "crooked", w[0]]) == 1
    assert table.contains("crooked")
    _, served = table.score_pairs([(w[0], "crooked")])
    assert served.all()


def test_quantize_rows_contract():
    """quantize_rows invariants the artifact format leans on: per-row
    symmetric scales, int8 range, norms taken over the QUANTIZED row
    (so lookup and fused scoring divide by the same quantity), and
    zero rows survive without NaN."""
    rng = np.random.default_rng(11)
    emb = rng.normal(size=(8, 32)).astype(np.float32)
    emb[3] = 0.0
    q, scales, norms = quantize_rows(emb)
    assert q.dtype == np.int8 and q.shape == emb.shape
    assert scales.dtype == np.float32 and norms.dtype == np.float32
    assert int(np.abs(q).max()) <= 127
    expect_norms = np.maximum(
        np.linalg.norm(q.astype(np.float32), axis=-1), 1e-8)
    assert np.allclose(norms, expect_norms)
    assert np.all(np.isfinite(q[3].astype(np.float32) / norms[3]))
    # round-trip: dequantized rows track the originals
    deq = q.astype(np.float32) * scales[:, None]
    keep = np.arange(8) != 3
    cos = np.sum(_unit(deq)[keep] * _unit(emb)[keep], axis=-1)
    assert float(cos.min()) > 0.99


def test_wordlist_payload_identity_cache():
    """Satellite: /wordlist's serialized payload + ETag are computed
    once per lexicon OBJECT — repeated calls return the same bytes
    object, and clearing the assets cache (a regenerated lexicon)
    recomputes instead of serving the stale payload forever."""
    from cassmantle_tpu.server import app as app_mod

    p1 = app_mod._wordlist_payload()
    e1 = app_mod._wordlist_etag()
    assert app_mod._wordlist_payload() is p1
    assert app_mod._wordlist_etag() == e1

    load_wordlist.cache_clear()
    try:
        p2 = app_mod._wordlist_payload()
        assert p2 is not p1          # recomputed for the new identity
        assert p2 == p1              # same lexicon content -> same bytes
        assert app_mod._wordlist_etag() == e1
        assert app_mod._wordlist_payload() is p2
    finally:
        load_wordlist.cache_clear()
