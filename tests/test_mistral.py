"""Mistral-family LM tests: RoPE/GQA/sliding-window semantics, the
prefill+cached-decode contract vs the plain forward, checkpoint
conversion, TP sharding, and the serving PromptGenerator wiring.

The reference uses hosted Mistral-7B-Instruct for prompt generation
(reference backend.py:25, 240-268); these tests cover the local
TPU-native replacement at tiny dims.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.config import MistralConfig
from cassmantle_tpu.models.mistral import (
    MistralLM,
    apply_rope,
    band_mask,
    repeat_kv,
    rope_tables,
)

CFG = MistralConfig.tiny()


@pytest.fixture(scope="module")
def model_and_params():
    model = MistralLM(CFG)
    ids = jnp.zeros((1, 8), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    return model, params


def test_rope_rotation_preserves_norm_and_relative_angles():
    cos, sin = rope_tables(jnp.arange(6), 8, 10000.0)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 6, 2, 8))
    rot = apply_rope(x, cos, sin)
    # rotations preserve per-pair L2 norm
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(rot), axis=-1),
        rtol=1e-5,
    )
    # position 0 is the identity rotation
    np.testing.assert_allclose(
        np.asarray(rot[:, 0]), np.asarray(x[:, 0]), rtol=1e-6, atol=1e-6
    )
    # dot products depend only on relative offset: <r(q,i), r(k,i+d)>
    # equal for all i
    q = jax.random.normal(jax.random.PRNGKey(2), (8,))
    k = jax.random.normal(jax.random.PRNGKey(3), (8,))
    cos6, sin6 = rope_tables(jnp.arange(6), 8, 10000.0)
    qr = apply_rope(jnp.tile(q, (1, 6, 1, 1)), cos6, sin6)[0, :, 0]
    kr = apply_rope(jnp.tile(k, (1, 6, 1, 1)), cos6, sin6)[0, :, 0]
    dots = [float(qr[i] @ kr[i + 2]) for i in range(4)]
    np.testing.assert_allclose(dots, dots[0] * np.ones(4), rtol=1e-4)


def test_band_mask_window():
    m = np.asarray(band_mask(jnp.arange(5), jnp.arange(5), 2))
    expected = np.array([
        [1, 0, 0, 0, 0],
        [1, 1, 0, 0, 0],
        [0, 1, 1, 0, 0],
        [0, 0, 1, 1, 0],
        [0, 0, 0, 1, 1],
    ], dtype=bool)
    np.testing.assert_array_equal(m, expected)


def test_repeat_kv():
    kv = jnp.arange(2 * 3 * 2 * 4).reshape(2, 3, 2, 4).astype(jnp.float32)
    rep = repeat_kv(kv, 2)
    assert rep.shape == (2, 3, 4, 4)
    np.testing.assert_array_equal(np.asarray(rep[:, :, 0]),
                                  np.asarray(rep[:, :, 1]))
    np.testing.assert_array_equal(np.asarray(rep[:, :, 0]),
                                  np.asarray(kv[:, :, 0]))


def test_forward_shapes_and_finite(model_and_params):
    model, params = model_and_params
    ids = jax.random.randint(jax.random.PRNGKey(4), (2, 12), 0,
                             CFG.vocab_size)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 12, CFG.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_prefill_matches_forward(model_and_params):
    """Prefill's last-real-token logits == full forward at that position,
    including for right-padded rows."""
    model, params = model_and_params
    b, p, max_len = 2, 8, 12
    ids = jax.random.randint(jax.random.PRNGKey(5), (b, p), 0,
                             CFG.vocab_size)
    plen = jnp.asarray([8, 5], dtype=jnp.int32)
    last, cache = model.apply(params, ids, plen, max_len,
                              method=MistralLM.prefill)
    assert len(cache) == CFG.num_layers
    assert cache[0][0].shape == (b, max_len, CFG.num_kv_heads, CFG.head_dim)

    valid = jnp.arange(p)[None, :] < plen[:, None]
    full = model.apply(params, ids, valid)
    for row in range(b):
        np.testing.assert_allclose(
            np.asarray(last[row]),
            np.asarray(full[row, int(plen[row]) - 1]),
            atol=1e-4, rtol=1e-4,
        )


def test_cached_decode_matches_forward(model_and_params):
    """Greedy continuation via prefill+decode_step equals recomputing the
    full forward each step — the KV-cache/RoPE/window contract."""
    model, params = model_and_params
    p, steps, max_len = 6, 4, 12
    ids = jax.random.randint(jax.random.PRNGKey(6), (1, p), 0,
                             CFG.vocab_size)
    plen = jnp.asarray([p], dtype=jnp.int32)

    last, cache = model.apply(params, ids, plen, max_len,
                              method=MistralLM.prefill)
    positions = jnp.arange(max_len)[None, :]
    seq = ids
    for i in range(steps):
        tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, tok[:, None]], axis=1)
        idx = jnp.int32(p + i)
        valid = positions <= idx
        last, cache = model.apply(params, tok, idx, cache, valid,
                                  method=MistralLM.decode_step)
        full = model.apply(params, seq)
        np.testing.assert_allclose(
            np.asarray(last[0]), np.asarray(full[0, -1]),
            atol=2e-4, rtol=2e-4,
        )


def test_sliding_window_limits_attention(model_and_params):
    """With window W, logits at position i are unchanged by tokens at
    positions <= i - W."""
    model, params = model_and_params
    w = CFG.sliding_window  # 16 in tiny config
    s = w + 4
    ids = jax.random.randint(jax.random.PRNGKey(7), (1, s), 0,
                             CFG.vocab_size)
    # perturb the earliest token: outside the window of the last position
    ids2 = ids.at[0, 0].set((ids[0, 0] + 1) % CFG.vocab_size)
    out1 = model.apply(params, ids)
    out2 = model.apply(params, ids2)
    # note: with >= 2 layers information propagates through intermediate
    # positions, so only a 1-layer check would be exact. Build a 1-layer
    # model to assert exact independence.
    one = dataclasses.replace(CFG, num_layers=1)
    m1 = MistralLM(one)
    p1 = m1.init(jax.random.PRNGKey(8), ids)
    o1 = m1.apply(p1, ids)
    o2 = m1.apply(p1, ids2)
    np.testing.assert_allclose(
        np.asarray(o1[0, -1]), np.asarray(o2[0, -1]), atol=1e-5, rtol=1e-5
    )
    # sanity: within-window positions DO see the change
    assert not np.allclose(np.asarray(o1[0, 1]), np.asarray(o2[0, 1]))
    del out1, out2


def test_greedy_decode_integration(model_and_params):
    from cassmantle_tpu.ops.decode import greedy_decode, make_apply_pair

    model, params = model_and_params
    ids = jax.random.randint(jax.random.PRNGKey(9), (2, 8), 0,
                             CFG.vocab_size)
    plen = jnp.asarray([8, 4], dtype=jnp.int32)
    tokens, gen_len = greedy_decode(
        make_apply_pair(model), params, ids, plen,
        jax.random.PRNGKey(0), 6, 0
    )
    assert tokens.shape == (2, 6)
    assert (np.asarray(gen_len) <= 6).all()


def test_convert_mistral_roundtrip(model_and_params):
    """Fabricate a torch-layout checkpoint from known Flax params and
    assert the converter reproduces them exactly."""
    from cassmantle_tpu.models.weights import convert_mistral

    model, params = model_and_params
    p = params["params"]
    src = {}
    src["model.embed_tokens.weight"] = np.asarray(p["embed"]["embedding"])
    for i in range(CFG.num_layers):
        b = p[f"block_{i}"]
        pre = f"model.layers.{i}"
        src[f"{pre}.input_layernorm.weight"] = np.asarray(b["ln1"]["scale"])
        src[f"{pre}.post_attention_layernorm.weight"] = np.asarray(
            b["ln2"]["scale"])
        for name, hf in (("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj"),
                         ("out", "o_proj")):
            src[f"{pre}.self_attn.{hf}.weight"] = np.asarray(
                b["attn"][name]["kernel"]).T
        for name, hf in (("gate", "gate_proj"), ("up", "up_proj"),
                         ("down", "down_proj")):
            src[f"{pre}.mlp.{hf}.weight"] = np.asarray(
                b["mlp"][name]["kernel"]).T
    src["model.norm.weight"] = np.asarray(p["ln_f"]["scale"])
    src["lm_head.weight"] = np.asarray(p["lm_head"]["kernel"]).T

    converted = convert_mistral(src, CFG.num_layers)
    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = jax.tree_util.tree_leaves_with_path(converted)
    assert len(flat_a) == len(flat_b)
    paths_a = {jax.tree_util.keystr(k): v for k, v in flat_a}
    paths_b = {jax.tree_util.keystr(k): v for k, v in flat_b}
    assert paths_a.keys() == paths_b.keys()
    for key, val in paths_a.items():
        np.testing.assert_array_equal(np.asarray(val),
                                      np.asarray(paths_b[key]), err_msg=key)

    # converted params actually run
    ids = jnp.zeros((1, 4), dtype=jnp.int32)
    out = model.apply(converted, ids)
    assert np.isfinite(np.asarray(out)).all()


def test_tp_sharding_rules_cover_mistral(model_and_params):
    from jax.sharding import PartitionSpec as P

    from cassmantle_tpu.parallel.sharding import param_specs

    _, params = model_and_params
    specs = param_specs(params)
    flat = {
        jax.tree_util.keystr(k): v
        for k, v in jax.tree_util.tree_leaves_with_path(specs)
    }
    get = lambda s: [v for k, v in flat.items() if s in k]
    assert all(s == P(None, "tp") for s in get("attn']['q']['kernel"))
    assert all(s == P(None, "tp") for s in get("mlp']['gate']['kernel"))
    assert all(s == P(None, "tp") for s in get("mlp']['up']['kernel"))
    assert all(s == P("tp", None) for s in get("mlp']['down']['kernel"))
    assert all(s == P("tp", None) for s in get("attn']['out']['kernel"))


def test_prompt_generator_mistral_family(tmp_path):
    """PromptGenerator serves the Mistral family end to end (byte
    tokenizer fallback, random weights): text comes back non-empty."""
    import dataclasses as dc

    from cassmantle_tpu.config import test_config
    from cassmantle_tpu.serving.pipeline import PromptGenerator

    base = test_config()
    cfg = base.replace(
        models=dc.replace(base.models, mistral=MistralConfig.tiny())
    )
    gen = PromptGenerator(cfg)
    from cassmantle_tpu.models.mistral import MistralLM as cls_check

    assert isinstance(gen.model, cls_check)
    text = gen.generate("An old ship left the harbor", max_new_tokens=4)
    assert isinstance(text, str) and len(text) > 0
