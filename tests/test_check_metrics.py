"""Metric-name drift gate: every literal metrics emission in the
package must follow the naming convention and appear in the
docs/OBSERVABILITY.md catalog (tools/check_metrics.py). Runs in the
fast tier so drift fails tier-1 before it ships."""

from tools.check_metrics import (
    _name_matches,
    check,
    extract_sites,
    load_catalog,
    load_catalog_types,
)


def test_package_metric_names_clean():
    assert check() == []


def test_catalog_is_nonempty():
    catalog = load_catalog()
    assert len(catalog) > 40          # the full serving surface
    assert "http.init" in catalog
    assert "circuit.<name>.opened" in catalog


def test_extractor_reads_fstrings_as_wildcards():
    sites = extract_sites(
        "metrics.inc(f'{self.name}.batches')\n"
        "metrics.observe('a.b_s', 1.0)\n"
        "metrics.timer(name)\n",            # dynamic: skipped
        "<test>")
    assert ("*.batches", "inc", 1) in sites
    assert ("a.b_s", "observe", 2) in sites
    assert len(sites) == 2


def test_extractor_covers_block_timer_stage_names():
    """block_timer emits a metric + stage span; its literal names must
    lint like any metrics.observe (the device-stage names this layer
    leans on — scorer.encode_s, pipeline.t2i_s — would otherwise drift
    off the catalog unchecked)."""
    sites = extract_sites(
        "with block_timer('scorer.encode_s') as sink:\n    pass\n",
        "<test>")
    assert ("scorer.encode_s", "observe", 1) in sites
    # the package-wide scan actually sees the real stage sites
    import pathlib

    from tools.check_metrics import PACKAGE

    all_names = set()
    for p in sorted(pathlib.Path(PACKAGE).rglob("*.py")):
        for name, _, _ in extract_sites(p.read_text(), str(p)):
            all_names.add(name)
    assert {"scorer.encode_s", "pipeline.t2i_s",
            "pipeline.sdxl_s", "pipeline.prompt_s"} <= all_names


def test_wildcard_matching_rules():
    assert _name_matches("circuit.*.*", "circuit.<name>.opened")
    assert _name_matches("score.batches", "<queue>.batches")
    assert _name_matches("store.lock_*", "store.lock_<kind>")
    assert not _name_matches("score.batches", "<queue>.items")
    assert not _name_matches("a.b.c", "a.b")


def test_violations_are_detected():
    bad = extract_sites("metrics.inc('UPPER.case')\n"
                        "metrics.inc('nosegments')\n"
                        "metrics.observe('a.no_unit', 1.0)\n", "<t>")
    # extraction itself keeps them; check() logic is exercised via the
    # package scan above — here pin the convention primitives
    assert ("UPPER.case", "inc", 1) in bad
    from tools.check_metrics import _SEGMENT

    assert not _SEGMENT.match("UPPER")
    assert _SEGMENT.match("lower_case_1")


def test_extractor_covers_injected_registry_receivers():
    """Modules taking the registry by injection (obs/slo.py,
    obs/process.py use ``self._registry``) must lint like direct
    ``metrics.`` emitters — the receiver rule is name-shaped, not
    import-shaped."""
    sites = extract_sites(
        "self._registry.gauge('slo.burning', 1.0)\n"
        "registry.inc('a.b')\n"
        "cluster_metrics.gauge('federation.peer_up', 1.0)\n"
        "unrelated.gauge('not.linted', 1.0)\n",
        "<t>")
    assert ("slo.burning", "gauge", 1) in sites
    assert ("a.b", "inc", 2) in sites
    assert ("federation.peer_up", "gauge", 3) in sites
    assert not any(name == "not.linted" for name, _, _ in sites)


def test_catalog_types_parsed_from_tables():
    types = load_catalog_types()
    assert types["http.init"] == "counter"
    assert types["round.remaining_s"] == "gauge"
    assert types["http.compute_score_s"] == "histogram"
    assert types["slo.burning"] == "gauge"
    # prose mentions outside typed table rows carry no type
    assert "slo.burn" not in types


def test_type_drift_is_a_lint_error():
    """An emission site whose call kind contradicts the catalog row's
    declared type (a counter quietly emitted as a gauge) fails the
    lint instead of shipping a broken exposition shape."""
    from cassmantle_tpu.analysis.core import parse_source, run_passes
    from cassmantle_tpu.analysis.metric_names import MetricNamePass

    drift = parse_source("metrics.inc('http.compute_score_s')\n", "<t>")
    findings = run_passes([drift], [MetricNamePass()])
    assert len(findings) == 1 and "type drift" in findings[0].message
    drift2 = parse_source("metrics.gauge('http.init', 1.0)\n", "<t>")
    assert any("type drift" in f.message
               for f in run_passes([drift2], [MetricNamePass()]))
    # the matching kind is clean; wildcard sites need only ONE matching
    # typed row of the right kind
    ok = parse_source(
        "metrics.observe('http.compute_score_s', 1.0)\n"
        "metrics.inc(f'{self.name}.batches')\n", "<t>")
    assert run_passes([ok], [MetricNamePass()]) == []
