"""Parity pins for the fused GroupNorm+SiLU+conv3x3 Pallas path.

The kernel runs in interpret mode on CPU (ops/fused_conv.py dispatch), so
these tests execute the REAL kernel logic, not a stand-in: per-shape
parity against the pure-lax reference (padded-channel case included),
param-tree identity between the fused and unfused ResBlock, ResBlock
output parity, and an end-to-end tiny SD1.5 pipeline A/B with the config
flag on vs off.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cassmantle_tpu.ops.fused_conv import (
    fused_conv_ok,
    gn_silu_conv3x3,
    gn_silu_conv3x3_reference,
    round_up,
)

# (B, H, W, C, F, pad_to) — covers an aligned case, a pad-to-128 case
# (C and F both round up), a ragged/odd-geometry case with small pad,
# and a rectangular image.
SHAPES = [
    (2, 8, 8, 32, 64, 0),
    (1, 16, 16, 96, 96, 128),   # padded: 96 -> 128 on both C and F
    (2, 6, 10, 40, 72, 8),      # rectangular + odd channels, pad to 8
    (1, 12, 12, 64, 32, 0),     # F < C, shrinking conv
    (1, 64, 64, 40, 48, 0),     # multi-row-tile: exercises halo DMA
]


def _case(rng, b, h, w, c, f):
    x = jnp.asarray(rng.standard_normal((b, h, w, c)), jnp.float32)
    a = jnp.asarray(rng.standard_normal((b, c)) * 0.5 + 1.0, jnp.float32)
    bb = jnp.asarray(rng.standard_normal((b, c)) * 0.1, jnp.float32)
    k = jnp.asarray(rng.standard_normal((3, 3, c, f)) * 0.05, jnp.float32)
    bias = jnp.asarray(rng.standard_normal((f,)) * 0.1, jnp.float32)
    return x, a, bb, k, bias


@pytest.mark.parametrize("b,h,w,c,f,pad", SHAPES)
def test_kernel_matches_reference(b, h, w, c, f, pad):
    rng = np.random.default_rng(hash((b, h, w, c, f)) % 2**32)
    x, a, bb, k, bias = _case(rng, b, h, w, c, f)
    ref = gn_silu_conv3x3_reference(x, a, bb, k, bias)
    got = gn_silu_conv3x3(x, a, bb, k, bias, pad_to=pad, interpret=True)
    assert got.shape == ref.shape and got.dtype == ref.dtype
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_padding_is_exact():
    """Channel padding is a layout trade, never a numeric one: padded
    and unpadded dispatch agree to roundoff."""
    rng = np.random.default_rng(7)
    x, a, bb, k, bias = _case(rng, 2, 8, 8, 40, 72)
    plain = gn_silu_conv3x3(x, a, bb, k, bias, pad_to=0, interpret=True)
    padded = gn_silu_conv3x3(x, a, bb, k, bias, pad_to=128, interpret=True)
    np.testing.assert_allclose(np.asarray(padded), np.asarray(plain),
                               atol=1e-5, rtol=1e-5)


def test_hot_shapes_dispatch_to_kernel():
    """The SD1.5-512 ResBlock shapes (64x64x320..8x8x2560 skip-concats,
    after pad-to-128) and the SDXL-1024 128x128 levels must all take
    the Pallas path — the whole point of the op; a silent fallback at
    the hot levels would make the sd15_fusedconv A/B measure nothing
    (this regression shipped once: a full-H block gate rejected every
    64x64 level)."""
    for h, w, c, f in [
        (64, 64, 384, 384), (64, 64, 1024, 384),   # SD1.5 level 0 (+concat)
        (32, 32, 640, 640), (32, 32, 1024, 640),
        (16, 16, 1280, 1280), (8, 8, 2560, 1280),
        (128, 128, 384, 384), (128, 128, 2560, 1280),  # SDXL-1024
    ]:
        # ShapeDtypeStructs: the gate is shape/dtype-only, no data needed
        x = jax.ShapeDtypeStruct((1, h, w, c), jnp.bfloat16)
        k = jax.ShapeDtypeStruct((3, 3, c, f), jnp.bfloat16)
        assert fused_conv_ok(x, k), (h, w, c, f)


def test_round_up():
    assert round_up(320, 128) == 384
    assert round_up(640, 128) == 640
    assert round_up(960, 128) == 1024
    assert round_up(7, 0) == 7


def test_dispatch_gate():
    """Shapes the kernel can't take fall back (and the fallback IS the
    reference, so the result is still correct)."""
    x = jnp.zeros((1, 2, 2, 8))          # too small for border taps
    k = jnp.zeros((3, 3, 8, 8))
    assert not fused_conv_ok(x, k)
    k5 = jnp.zeros((5, 5, 8, 8))
    assert not fused_conv_ok(jnp.zeros((1, 8, 8, 8)), k5)
    rng = np.random.default_rng(3)
    xs, a, bb, kk, bias = _case(rng, 1, 2, 2, 8, 8)
    out = gn_silu_conv3x3(xs, a, bb, kk, bias, interpret=True)
    ref = gn_silu_conv3x3_reference(xs, a, bb, kk, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_kill_switch(monkeypatch):
    rng = np.random.default_rng(5)
    x, a, bb, k, bias = _case(rng, 1, 8, 8, 32, 32)
    monkeypatch.setenv("CASSMANTLE_NO_FUSED_CONV", "1")
    out = gn_silu_conv3x3(x, a, bb, k, bias, pad_to=128, interpret=True)
    ref = gn_silu_conv3x3_reference(x, a, bb, k, bias)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_resblock_fused_param_tree_and_output_parity():
    """The fused ResBlock declares nn.Conv's EXACT param layout (same
    names, shapes, initializers, RNG folds) — checkpoints and the A/B
    share one tree — and reproduces the unfused outputs."""
    from cassmantle_tpu.models.unet import ResBlock

    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 32))
    temb = jax.random.normal(jax.random.PRNGKey(2), (2, 16))
    plain = ResBlock(64, jnp.float32)
    fused = ResBlock(64, jnp.float32, fused_conv=True, conv_pad_to=128)
    p_plain = plain.init(rng, x, temb)
    p_fused = fused.init(rng, x, temb)
    assert (jax.tree_util.tree_structure(p_plain)
            == jax.tree_util.tree_structure(p_fused))
    jax.tree_util.tree_map(
        lambda u, v: np.testing.assert_array_equal(
            np.asarray(u), np.asarray(v)),
        p_plain, p_fused)
    o_plain = plain.apply(p_plain, x, temb)
    o_fused = fused.apply(p_plain, x, temb)  # the SAME tree drives both
    np.testing.assert_allclose(np.asarray(o_fused), np.asarray(o_plain),
                               atol=5e-5, rtol=1e-4)


def test_unet_flag_parity(cfg):
    """Whole-UNet forward with fused_conv on vs off, same params."""
    import dataclasses

    from cassmantle_tpu.models.unet import UNet

    ucfg = cfg.models.unet
    plain = UNet(ucfg)
    fused = UNet(dataclasses.replace(ucfg, fused_conv=True,
                                     conv_pad_to=128))
    lat = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 16, 4))
    ts = jnp.asarray([10, 500])
    ctx = jax.random.normal(jax.random.PRNGKey(4),
                            (2, 8, ucfg.context_dim))
    params = plain.init(jax.random.PRNGKey(0), lat, ts, ctx)
    o_plain = plain.apply(params, lat, ts, ctx)
    o_fused = fused.apply(params, lat, ts, ctx)
    np.testing.assert_allclose(np.asarray(o_fused), np.asarray(o_plain),
                               atol=2e-4, rtol=1e-3)


@pytest.mark.slow
def test_pipeline_flag_parity(cfg):
    """End-to-end tiny SD1.5 pipeline: flag on vs off produce the same
    images within parity tolerance (uint8: tiny fp reorder deltas may
    flip a pixel value by ~1 step; the distributions must agree).

    Slow tier since round 25 (the default tier outgrew its 870s window
    again, same pressure as rounds 14/21): ~20s of paired pipeline
    compiles whose tier-1 coverage is duplicated — the unet-level flag
    parity above stays in the quick sweep, and the fused pipeline path
    is exercised end-to-end every tier-1 run by the w8a8 A/B tests
    (both arms of test_w8a8's pipeline tests run fused_conv=True)."""
    import dataclasses

    from cassmantle_tpu.serving.pipeline import Text2ImagePipeline

    pipe_off = Text2ImagePipeline(cfg)
    cfg_on = cfg.replace(models=dataclasses.replace(
        cfg.models, unet=dataclasses.replace(
            cfg.models.unet, fused_conv=True, conv_pad_to=128)))
    pipe_on = Text2ImagePipeline(cfg_on, share_params_with=pipe_off)
    prompts = ["a lighthouse over a stormy sea"]
    img_off = pipe_off.generate(prompts, seed=3)
    img_on = pipe_on.generate(prompts, seed=3)
    assert img_off.shape == img_on.shape
    diff = np.abs(img_off.astype(np.int32) - img_on.astype(np.int32))
    assert diff.max() <= 3, diff.max()
    assert diff.mean() < 0.1, diff.mean()
