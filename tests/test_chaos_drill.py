"""Seeded chaos-drill smoke (slow tier, ISSUE 12): the multi-process
drill phases from `bench.py chaos_drill` at reduced duration — real
worker processes over a real (and then replicated) mantlestore, a
seeded fault schedule, and the SIGTERM-handoff acceptance.

The fast in-process versions of every behavior here live in
tests/test_chaos.py, tests/test_fault_injection.py, and
tests/test_chaos_recovery.py; this module buys cross-process
integration at multi-second cost, like test_fabric_cluster."""

import pytest

import bench
from cassmantle_tpu.native.client import ensure_built

pytestmark = pytest.mark.skipif(
    ensure_built() is None, reason="no C++ toolchain"
)


def test_seeded_fault_phase_injects_and_keeps_serving():
    """A flaky-generation phase against real workers: the armed plan
    fires (scraped from the workers' /metrics), guesses keep landing,
    and the error budget stays bounded — skip-don't-crash under a
    replayable schedule."""
    stats = bench._drill_cluster_phase(
        "flaky_generation", "round.generate=flake:p=0.5", seed=42,
        base_port=8571, store_port=7571, rooms=3, sessions=3,
        seconds=2.5, round_seconds=1.5)
    assert stats["guesses"] > 20
    assert stats["injections"] >= 1, "the armed plan never fired"
    total = stats["guesses"] + stats["errors"]
    assert stats["errors"] <= total * 0.05


def test_store_leader_kill_recovers_within_grace():
    """The leader-kill phase: the replicated pair's leader dies under
    load; the workers fail over and requests succeed again well inside
    the failover grace (recovery_s is the drill's headline number)."""
    stats = bench._drill_cluster_phase(
        "leader_kill", "", seed=42, base_port=8576, store_port=7576,
        rooms=3, sessions=3, seconds=3.0, kill_leader=True)
    assert stats["guesses"] > 20
    assert stats["recovery_s"] is not None
    assert stats["recovery_s"] < 15.0, (
        f"failover took {stats['recovery_s']}s")


def test_sigterm_handoff_adopts_rooms_and_preserves_scores():
    """The ISSUE 12 handoff acceptance against real processes: the
    SIGTERM'd worker's rooms are adopted by the survivor as part of
    the handoff (adoption lands in well under the membership
    staleness TTL — the TTL path would take seconds longer), and a
    score accepted before the signal is served by the survivor after
    it — no lost accepted scores."""
    stats = bench._drill_sigterm_handoff_phase(
        base_port=8581, store_port=7581, rooms=3)
    assert "error" not in stats, stats
    assert stats["score_preserved"] is True
    assert stats["adoption_s"] is not None
    # the graceful handoff moved the rooms, not the staleness TTL:
    # TTL-driven adoption cannot land before ttl_s (2.5s) + a beat
    assert stats["adoption_s"] < stats["membership_ttl_s"]
    # handoff() returns only after observing the adopting beat, so
    # exit follows adoption by construction; the external poll
    # usually catches it live too (informational, racy at ~30ms)
    assert stats["handoff_exit_s"] >= stats["adoption_s"]


def test_wedged_dispatch_watchdog_recovers():
    """The in-process wedged-dispatch phase: a chaos wedge on the real
    dispatch thread -> deadline failure + watchdog fire + thread
    replacement, and post-release dispatch recovers in milliseconds."""
    stats = bench._drill_wedged_dispatch_phase(seed=42)
    assert stats["deadline_failures"] == 1
    assert stats["watchdog_fired"] is True
    assert stats["recovery_s"] < 5.0
