/* Execute static/spell.js — the REAL file, in a real JS runtime — over
 * golden cases, printing {word: {check, suggest}} JSON for the Python
 * side (tests/test_js_runtime.py) to compare against utils/spell.py.
 * The lockstep contract between the two implementations is otherwise
 * only enforced by rule-set text parity (test_spell_rule_parity);
 * this runs the actual code.
 *
 * Usage: node run_spell.js <wordlist.txt>   (cases JSON on stdin)
 */

"use strict";

const fs = require("fs");
const path = require("path");
const vm = require("vm");

const wordlistPath = process.argv[2];
const words = fs.readFileSync(wordlistPath, "utf8")
  .split("\n").map((w) => w.trim()).filter(Boolean);

globalThis.window = globalThis;
const spellSrc = fs.readFileSync(
  path.join(__dirname, "..", "..", "static", "spell.js"), "utf8");
vm.runInThisContext(spellSrc, { filename: "spell.js" });

const spell = new window.Spell(words);
const cases = JSON.parse(fs.readFileSync(0, "utf8"));
const out = {};
for (const word of cases) {
  out[word] = { check: spell.check(word), suggest: spell.suggest(word, 3) };
}
process.stdout.write(JSON.stringify(out));
