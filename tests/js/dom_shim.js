/* Minimal DOM shim for driving static/app.js under node (no browser).
 *
 * Implements exactly the surface app.js touches — getElementById,
 * createElement/createTextNode, appendChild, classList
 * (add/remove/toggle/contains with force semantics), textContent,
 * innerHTML-clear, dataset, addEventListener/click dispatch,
 * querySelectorAll for the two selectors the client uses
 * ("#prompt input", ".privacy-link") — plus browser globals: a
 * cookie-jar fetch against the real server, a capturable WebSocket,
 * localStorage, and location. Element ids come from the REAL
 * static/index.html so a renamed id fails here the way it would fail
 * in a browser.
 *
 * Used by run_app.js; skipped entirely when node is absent
 * (tests/test_js_runtime.py gates on shutil.which("node")).
 */

"use strict";

class ClassList {
  constructor() { this._set = new Set(); }
  add(...cs) { cs.forEach((c) => this._set.add(c)); }
  remove(...cs) { cs.forEach((c) => this._set.delete(c)); }
  contains(c) { return this._set.has(c); }
  toggle(c, force) {
    const want = force === undefined ? !this._set.has(c) : !!force;
    if (want) this._set.add(c); else this._set.delete(c);
    return want;
  }
}

class Element {
  constructor(tag) {
    this.tagName = String(tag || "div").toUpperCase();
    this.children = [];
    this.classList = new ClassList();
    this.dataset = {};
    this.textContent = "";
    this.value = "";
    this.listeners = {};
    this.parent = null;
  }
  set className(v) {
    this.classList = new ClassList();
    String(v).split(/\s+/).filter(Boolean)
      .forEach((c) => this.classList.add(c));
  }
  get className() { return [...this.classList._set].join(" "); }
  set innerHTML(v) {
    if (v === "") this.children = [];
    else throw new Error("shim supports innerHTML='' only");
  }
  appendChild(child) {
    if (child && child.nodeType !== 3) child.parent = this;
    this.children.push(child);
    return child;
  }
  addEventListener(type, fn) {
    (this.listeners[type] = this.listeners[type] || []).push(fn);
  }
  dispatch(type, ev) {
    (this.listeners[type] || []).forEach((fn) => fn({
      preventDefault() {}, target: this, key: "", ...ev,
    }));
  }
  click() { this.dispatch("click", {}); }
  *walk() {
    for (const c of this.children) {
      if (c && c.nodeType !== 3) { yield c; yield* c.walk(); }
    }
  }
}

function setupDom(base, indexHtml) {
  const byId = new Map();
  // ids AND initial classes from the real page, so renames break the
  // harness like a browser — and "game starts hidden" is really true
  for (const m of indexHtml.matchAll(/<(\w+)([^>]*)\bid="([^"]+)"([^>]*)>/g)) {
    const el = new Element(m[1]);
    const cls = (m[2] + m[4]).match(/class="([^"]*)"/);
    if (cls) el.className = cls[1];
    byId.set(m[3], el);
  }
  const privacyLink = new Element("a");
  privacyLink.className = "privacy-link";

  const documentEl = {
    getElementById: (id) => byId.get(id) || null,
    createElement: (tag) => new Element(tag),
    createTextNode: (text) => ({ nodeType: 3, text }),
    addEventListener: () => {},
    querySelectorAll: (sel) => {
      const m = sel.match(/^#([\w-]+)\s+(\w+)$/);
      if (m) {
        const root = byId.get(m[1]);
        if (!root) return [];
        return [...root.walk()].filter(
          (e) => e.tagName === m[2].toUpperCase());
      }
      if (sel.startsWith(".")) {
        const cls = sel.slice(1);
        const all = [privacyLink, ...byId.values()];
        return all.filter((e) => e.classList.contains(cls));
      }
      return [];
    },
  };

  // cookie-jar fetch: node's fetch has no browser cookie store, but
  // the client relies on the aiohttp session cookie riding every call
  const jar = {};
  const realFetch = globalThis.fetch.bind(globalThis);
  const cookieFetch = async (url, opts = {}) => {
    const full = url.startsWith("http") ? url : base + url;
    const headers = { ...(opts.headers || {}) };
    const cookie = Object.entries(jar)
      .map(([k, v]) => `${k}=${v}`).join("; ");
    if (cookie) headers.Cookie = cookie;
    const res = await realFetch(full, { ...opts, headers });
    const setCookies = res.headers.getSetCookie
      ? res.headers.getSetCookie() : [];
    for (const line of setCookies) {
      const [kv] = line.split(";");
      const eq = kv.indexOf("=");
      if (eq > 0) jar[kv.slice(0, eq).trim()] = kv.slice(eq + 1).trim();
    }
    return res;
  };

  const sockets = [];
  class FakeWebSocket {
    constructor(url) { this.url = url; sockets.push(this); }
    send() {}
    close() {}
  }

  const store = {};
  const dom = {
    byId, sockets, jar, privacyLink,
    $ : (id) => byId.get(id),
    fire(type, sel, ev) { byId.get(sel).dispatch(type, ev); },
  };

  Object.assign(globalThis, {
    document: documentEl,
    window: globalThis,
    location: new URL(base),
    localStorage: {
      getItem: (k) => (k in store ? store[k] : null),
      setItem: (k, v) => { store[k] = String(v); },
    },
    WebSocket: FakeWebSocket,
    fetch: cookieFetch,
  });
  return dom;
}

module.exports = { setupDom, Element };
