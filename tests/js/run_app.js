/* Drive static/app.js — the REAL client code — against a REAL running
 * server (the --fake backend), asserting the flows the contract tests
 * (tests/test_frontend.py) can only grep for: boot, the per-word
 * spellcheck hold, guess scoring feedback, the win banner, and the
 * ws-reset refetch. Prints one JSON line of scenario results;
 * any assertion failure exits nonzero.
 *
 * Usage: node run_app.js <base-url> <answers-json>
 *   answers-json: {"<maskIdx>": "<exact word>", ...} — computed by the
 *   pytest side from the deterministic fake backend.
 */

"use strict";

const fs = require("fs");
const path = require("path");
const vm = require("vm");

const base = process.argv[2];
const answers = JSON.parse(process.argv[3] || "{}");
const STATIC = path.join(__dirname, "..", "..", "static");

const { setupDom } = require("./dom_shim.js");
const dom = setupDom(base, fs.readFileSync(
  path.join(STATIC, "index.html"), "utf8"));

vm.runInThisContext(
  fs.readFileSync(path.join(STATIC, "spell.js"), "utf8"),
  { filename: "spell.js" });
globalThis.Spell = window.Spell;

const results = {};
const assert = (cond, label) => {
  results[label] = !!cond;
  if (!cond) {
    process.stderr.write(`FAIL: ${label}\n` + JSON.stringify(results));
    process.exit(1);
  }
};
const sleep = (ms) => new Promise((r) => setTimeout(r, ms));
async function waitFor(fn, label, timeoutMs = 30000) {
  const t0 = Date.now();
  while (Date.now() - t0 < timeoutMs) {
    if (fn()) return;
    await sleep(50);
  }
  assert(false, `timeout: ${label}`);
}

(async () => {
  const $ = dom.$;

  vm.runInThisContext(
    fs.readFileSync(path.join(STATIC, "app.js"), "utf8"),
    { filename: "app.js" });

  // ---- boot: splash -> game, inputs rendered at mask indices ----
  await waitFor(() => !$("game").classList.contains("hidden"),
                "boot: game visible");
  assert($("splash").classList.contains("hidden"), "boot: splash hidden");
  const inputs = document.querySelectorAll("#prompt input");
  assert(inputs.length >= 1, "boot: mask inputs rendered");
  assert(Object.keys(answers).length >= inputs.length,
         "boot: answers cover masks");

  // ---- consent flow (first visit: notice shown, ok hides it) ----
  assert(!$("consent").classList.contains("hidden"), "consent: shown");
  $("consent-ok").click();
  assert($("consent").classList.contains("hidden"), "consent: dismissed");

  // ---- spellcheck hold: first submit of a misspelled word is held,
  // the SAME word resubmitted goes through (per-word escape hatch) ----
  inputs.forEach((inp) => { inp.value = ""; });
  inputs[0].value = "lighthosue";
  $("submit").click();
  await waitFor(() => $("feedback").textContent.includes("unusual word"),
                "hold: flagged once");
  $("submit").click();  // same word again -> sent to the server
  await waitFor(() => !$("feedback").textContent.includes("unusual word"),
                "hold: resubmit goes through");

  // ---- scoring feedback for a wrong-but-valid guess ----
  // (re-query: the scored submit above re-rendered #prompt's inputs)
  const inputs2 = document.querySelectorAll("#prompt input");
  inputs2.forEach((inp) => { inp.value = "stormy"; });
  $("submit").click();
  await waitFor(() => /close|cold/.test($("feedback").textContent),
                "score: feedback rendered");

  // ---- win flow: exact answers -> banner ----
  const inputsNow = document.querySelectorAll("#prompt input");
  inputsNow.forEach((inp) => { inp.value = answers[inp.dataset.mask]; });
  $("submit").click();
  await waitFor(() => !$("win-banner").classList.contains("hidden"),
                "win: banner shown");

  // ---- ws reset: clock renders, state clears, content refetched ----
  const ws = dom.sockets[dom.sockets.length - 1];
  assert(ws && ws.url.endsWith("/clock"), "ws: clock socket opened");
  ws.onmessage({ data: JSON.stringify(
    { time: "00:30", conns: 3, reset: false }) });
  assert($("clock").textContent === "00:30", "ws: clock text");
  assert($("clock").classList.contains("blink"), "ws: blink under 60s");
  assert($("player-count").textContent === "3", "ws: player count");
  ws.onmessage({ data: JSON.stringify(
    { time: "15:00", conns: 3, reset: true }) });
  await waitFor(() => $("win-banner").classList.contains("hidden"),
                "reset: banner cleared");
  assert(!$("clock").classList.contains("blink"), "reset: blink off");
  assert($("feedback").textContent === "", "reset: feedback cleared");

  process.stdout.write(JSON.stringify(results));
  process.exit(0);
})().catch((e) => {
  process.stderr.write(String(e.stack || e) + "\n" +
                       JSON.stringify(results));
  process.exit(1);
});
