"""HTTP/WS API tests: full server over the fake backend (fast rounds)."""

import asyncio
import base64
import dataclasses

import pytest
from aiohttp.test_utils import TestClient, TestServer

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.engine.content import (
    FakeContentBackend,
    hash_embed,
    hash_similarity,
)
from cassmantle_tpu.engine.game import Game
from cassmantle_tpu.engine.store import MemoryStore
from cassmantle_tpu.server.app import create_app


def make_cfg(time_per_prompt=30.0, rate=1000.0):
    cfg = _tiny_config()
    return cfg.replace(game=dataclasses.replace(
        cfg.game, time_per_prompt=time_per_prompt,
        rate_limit_default=rate, rate_limit_api=rate,
    ))


async def make_client(cfg, start_timer=False):
    game = Game(cfg, MemoryStore(), FakeContentBackend(image_size=32),
                hash_embed, hash_similarity)
    app = create_app(game, cfg, start_timer=start_timer)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client, game


@pytest.mark.asyncio
async def test_init_and_status_flow():
    client, _ = await make_client(make_cfg())
    try:
        res = await client.get("/client/status")
        assert (await res.json())["needInitialization"] is True

        res = await client.get("/init")
        data = await res.json()
        assert "session_id" in data
        assert "session_id" in res.cookies

        res = await client.get("/client/status")
        data = await res.json()
        assert data == {"won": 0, "needInitialization": False}
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_fetch_contents_shape():
    client, _ = await make_client(make_cfg())
    try:
        await client.get("/init")
        res = await client.get("/fetch/contents")
        data = await res.json()
        assert set(data) == {"image", "prompt", "story"}
        # image is valid base64 jpeg
        raw = base64.b64decode(data["image"])
        assert raw[:2] == b"\xff\xd8"
        prompt = data["prompt"]
        assert prompt["tokens"] and len(prompt["masks"]) == 2
        for m in prompt["masks"]:
            assert prompt["tokens"][m] == "*"
        assert data["story"]["title"]
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_compute_score_and_win():
    client, game = await make_client(make_cfg())
    try:
        await client.get("/init")
        current = await game.rounds.fetch_current_prompt()
        masks = current["masks"]

        res = await client.post(
            "/compute_score",
            json={"inputs": {str(masks[0]): "zzzz"}},
        )
        scores = await res.json()
        assert scores["won"] == 0

        answers = {str(m): current["tokens"][m] for m in masks}
        res = await client.post("/compute_score", json={"inputs": answers})
        scores = await res.json()
        assert scores["won"] == 1

        res = await client.get("/fetch/contents")
        prompt = (await res.json())["prompt"]
        assert prompt["masks"] == []
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_compute_score_bad_body():
    client, _ = await make_client(make_cfg())
    try:
        await client.get("/init")
        res = await client.post("/compute_score", data=b"not json")
        assert res.status == 400
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_clock_websocket_and_reset_flow():
    cfg = make_cfg(time_per_prompt=2.0)
    client, game = await make_client(cfg, start_timer=True)
    try:
        await client.get("/init")
        ws = await client.ws_connect("/clock")
        saw_reset = False
        for _ in range(12):
            msg = await asyncio.wait_for(ws.receive_json(), timeout=5.0)
            assert set(msg) == {"time", "reset", "conns"}
            assert ":" in msg["time"]
            if msg["reset"]:
                saw_reset = True
                break
        assert saw_reset, "round rollover never signalled reset"
        await ws.close()
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_rate_limit_enforced():
    cfg = make_cfg(rate=1000.0)
    cfg = cfg.replace(game=dataclasses.replace(cfg.game, rate_limit_api=2.0))
    client, _ = await make_client(cfg)
    try:
        statuses = []
        for _ in range(8):
            res = await client.get("/client/status")
            statuses.append(res.status)
        assert 429 in statuses
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_metrics_endpoint():
    client, _ = await make_client(make_cfg())
    try:
        await client.get("/init")
        res = await client.get("/metrics")
        data = await res.json()
        assert {"counters", "gauges", "timings"} <= set(data)
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_wordlist_endpoint():
    client, _ = await make_client(make_cfg())
    try:
        res = await client.get("/wordlist")
        data = await res.json()
        assert "the" in data["stopwords"]
        # the dictionary backing client spellcheck (static/spell.js)
        assert len(data["words"]) > 500
        assert "stormy" in data["words"]
        # seed/style vocabulary is always guessable
        assert "watercolor" in data["words"]
        # cache contract: content-hash ETag + revalidation, so a
        # redeployed lexicon invalidates browser caches immediately
        etag = res.headers["ETag"]
        assert "no-cache" in res.headers["Cache-Control"]
        res2 = await client.get("/wordlist",
                                headers={"If-None-Match": etag})
        assert res2.status == 304
        assert res2.headers["ETag"] == etag
        # a compressing proxy may weaken the validator; clients echo
        # W/"..." (possibly in a list) and must still get the 304
        weak = await client.get("/wordlist", headers={
            "If-None-Match": f'W/{etag}, "other"'})
        assert weak.status == 304
        res3 = await client.get("/wordlist",
                                headers={"If-None-Match": '"stale"'})
        assert res3.status == 200
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_healthz_endpoint():
    client, _ = await make_client(make_cfg())
    try:
        res = await client.get("/healthz")
        data = await res.json()
        assert res.status == 200
        assert data["ok"] is True and data["store"] is True \
            and data["device"] is True
        # the supervisor block rides along for operators (ISSUE 2)
        sup = data["supervisor"]
        assert sup["state"] == "ok"
        assert set(sup["breakers"]) == {"content", "score"}
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_readyz_ok_then_degraded_then_recovered():
    """/readyz is the supervisor verdict: 200 while healthy; 503 +
    Retry-After with breaker detail while the content breaker is open;
    200 again once the breaker closes (recovery)."""
    client, game = await make_client(make_cfg())
    try:
        res = await client.get("/readyz")
        data = await res.json()
        assert res.status == 200
        assert data["ready"] is True and data["store"] is True

        breaker = game.supervisor.content_breaker
        for _ in range(breaker.failure_threshold):
            breaker.record_failure()
        res = await client.get("/readyz")
        data = await res.json()
        assert res.status == 503
        assert data["ready"] is False and data["state"] == "degraded"
        assert data["breakers"]["content"]["state"] == "open"
        assert int(res.headers["Retry-After"]) >= 1

        breaker.record_success()
        res = await client.get("/readyz")
        assert res.status == 200
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_compute_score_degrades_through_hedge_ladder():
    """ISSUE 12 failover ladder at the HTTP layer: with the score
    breaker open and NO fabric peers, /compute_score answers 200 with
    floor-grade scores marked ``X-Score-Degraded`` (floor is the LAST
    resort, not a 503 to the player) — while a request that is itself
    a peer's HEDGE sheds 503 + Retry-After so hedges can never
    cascade. Recovery drops the marker."""
    client, game = await make_client(make_cfg())
    try:
        await client.get("/init")
        breaker = game.supervisor.score_breaker
        for _ in range(breaker.failure_threshold):
            breaker.record_failure()
        # a player request: no healthy peer exists (legacy one-worker
        # wrap) so the ladder bottoms out at marked floor scores
        res = await client.post("/compute_score",
                                json={"inputs": {"0": "word"}})
        assert res.status == 200
        assert res.headers["X-Score-Degraded"] == "floor"
        # a HEDGED request must not re-hedge or floor: honest 503 so
        # the origin worker tries its next peer
        res = await client.post("/compute_score",
                                json={"inputs": {"0": "word"}},
                                headers={"X-Score-Hedge": "1"})
        assert res.status == 503
        assert int(res.headers["Retry-After"]) >= 1
        breaker.record_success()
        res = await client.post("/compute_score",
                                json={"inputs": {"0": "word"}})
        assert res.status == 200
        assert "X-Score-Degraded" not in res.headers
    finally:
        await client.close()


def test_rate_limiter_eviction_preserves_active_buckets():
    """Overflow eviction is targeted: a busy client's half-spent bucket
    survives a table overflow — the old clear() reset EVERY bucket and
    admitted a synchronized burst (ISSUE 2 satellite)."""
    from cassmantle_tpu.server.ratelimit import RateLimiter

    limiter = RateLimiter(max_entries=100, stale_s=1000.0)
    # the active client spends its whole burst at rate 1 -> next call
    # would be denied unless its bucket gets (wrongly) reset
    assert limiter.allow("active-ip", "/api", rate=1.0)
    assert not limiter.allow("active-ip", "/api", rate=1.0)
    for i in range(200):                      # force repeated overflow
        limiter.allow(f"ip-{i}", "/api", rate=1.0)
        # the active client keeps hitting, so it is never the idle tail
        limiter.allow("active-ip", "/api", rate=1.0)
    assert len(limiter._buckets) <= 101       # capped, not unbounded
    # the active client's spent bucket must NOT have been flushed back
    # to a full burst by eviction
    assert not limiter.allow("active-ip", "/api", rate=1.0)


def test_rate_limiter_evicts_stale_first():
    import time as _time

    from cassmantle_tpu.server.ratelimit import RateLimiter

    limiter = RateLimiter(max_entries=10, stale_s=0.01)
    for i in range(10):
        limiter.allow(f"old-{i}", "/", rate=1.0)
    _time.sleep(0.02)                         # all 10 go stale
    limiter.allow("fresh", "/", rate=1.0)     # overflow -> stale purge
    assert ("fresh", "/") in limiter._buckets
    assert all(not k[0].startswith("old-") for k in limiter._buckets)


def test_rate_limiter_session_room_key_shape():
    """ISSUE 8 satellite: buckets are namespaced by (client, room), so
    one noisy room drains only its own quota — the same client's
    allowance in another room is untouched — and eviction at the new
    key shape stays targeted (the active (client, room) pair survives
    an overflow with its spent tokens)."""
    from cassmantle_tpu.server.ratelimit import RateLimiter

    limiter = RateLimiter(max_entries=100, stale_s=1000.0)
    # room A's burst spends; room B (same session) is unaffected
    assert limiter.allow(("s1", "lobby"), "/compute_score", rate=1.0)
    assert not limiter.allow(("s1", "lobby"), "/compute_score", rate=1.0)
    assert limiter.allow(("s1", "room-1"), "/compute_score", rate=1.0)
    # same (session, room), different route class: its own bucket too
    assert limiter.allow(("s1", "lobby"), "/init", rate=1.0)
    # overflow eviction: the busy pair keeps its SPENT bucket while
    # one-shot pairs overflow the table around it
    for i in range(200):
        limiter.allow((f"s-{i}", "room-1"), "/compute_score", rate=1.0)
        limiter.allow(("s1", "lobby"), "/compute_score", rate=1.0)
    assert len(limiter._buckets) <= 101
    assert not limiter.allow(("s1", "lobby"), "/compute_score", rate=1.0)
    assert (("s1", "lobby"), "/compute_score") in limiter._buckets


@pytest.mark.asyncio
async def test_rate_limit_keys_include_room_over_http():
    """End-to-end at the middleware: the same client exhausting room A's
    API quota still gets requests through in room B. Needs a real
    multi-room fabric — the legacy one-Game wrap deliberately pins
    itself to a single room."""
    from cassmantle_tpu.fabric.rooms import RoomFabric

    cfg = make_cfg()
    cfg = cfg.replace(game=dataclasses.replace(
        cfg.game, rate_limit_api=2.0, rate_limit_default=1000.0),
        fabric=dataclasses.replace(cfg.fabric, num_rooms=2))
    store = MemoryStore()

    def factory(room, room_store):
        return Game(cfg, room_store, FakeContentBackend(image_size=32),
                    hash_embed, hash_similarity)

    fabric = RoomFabric(cfg, store, factory, start_timers=False,
                        heartbeat=False)
    app = create_app(fabric, cfg, start_timer=False)
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        statuses_a = []
        for _ in range(5):
            res = await client.get(
                "/client/status", params={"room": "lobby",
                                          "session": "s1"})
            statuses_a.append(res.status)
        assert 429 in statuses_a          # room A quota exhausted
        res = await client.get(
            "/client/status", params={"room": "room-1", "session": "s1"})
        assert res.status == 200          # room B quota untouched
    finally:
        await client.close()


def test_device_health_probe():
    from cassmantle_tpu.utils.health import DeviceHealth

    h = DeviceHealth(timeout_s=60.0, cache_s=0.0)
    ok, _ = h.check()
    assert ok  # CPU device answers the probe
    # cached path
    h2 = DeviceHealth(timeout_s=60.0, cache_s=60.0)
    assert h2.check()[0] and h2.check()[0]


def test_device_health_timeout_marks_unhealthy(monkeypatch):
    import cassmantle_tpu.utils.health as health_mod

    def hang():
        import time as t

        t.sleep(0.5)
        return True

    monkeypatch.setattr(health_mod, "_probe_once", hang)
    h = health_mod.DeviceHealth(timeout_s=0.2, cache_s=0.0)
    ok, _ = h.check()
    assert not ok


@pytest.mark.asyncio
async def test_index_served():
    client, _ = await make_client(make_cfg())
    try:
        res = await client.get("/")
        assert res.status == 200
        text = await res.text()
        assert "CassMantle" in text
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_debug_trace_endpoint(tmp_path, monkeypatch):
    """POST /debug/trace captures a jax.profiler trace while traffic
    runs and is single-flight + loopback-guarded. The write path is the
    operator-set root + a sanitized name — never a request-chosen path."""
    monkeypatch.setenv("CASSMANTLE_TRACE_ROOT", str(tmp_path))
    client, _ = await make_client(make_cfg())
    try:
        res = await client.post("/debug/trace?seconds=0.2&name=tr")
        assert res.status == 200
        data = await res.json()
        assert data["trace_dir"] == str(tmp_path / "tr")
        import os as _os

        assert _os.path.isdir(data["trace_dir"])      # trace written
        res = await client.post("/debug/trace?seconds=abc")
        assert res.status == 400
        # path traversal in name is rejected, not written
        res = await client.post("/debug/trace?seconds=0.1&name=../evil")
        assert res.status == 400
    finally:
        await client.close()


def test_build_game_rejects_unknown_store_address():
    from cassmantle_tpu.server.app import build_game

    with pytest.raises(ValueError, match="store address"):
        build_game(make_cfg(), fake=True, store_addr="redis:6379")


@pytest.mark.asyncio
async def test_index_ships_privacy_modal():
    """Reference surface parity: the page carries a privacy-policy modal
    wired to link(s) (reference index.html ships the same surface)."""
    client, _ = await make_client(make_cfg())
    try:
        res = await client.get("/")
        text = await res.text()
        assert 'id="privacy-modal"' in text
        assert text.count('class="privacy-link"') >= 2   # consent + footer
        assert 'id="privacy-close"' in text
    finally:
        await client.close()


@pytest.mark.asyncio
async def test_full_stack_real_backend_round():
    """The one seam the fake-backend tests can't cover: HTTP -> engine
    -> REAL serving stack (tiny CLIP->DDIM->VAE pipeline, GPT-2 prompt
    decode, MiniLM guess scorer) end to end. A client initializes,
    fetches a genuinely generated round image, and scores a guess
    against the real embedding scorer."""
    from cassmantle_tpu.server.app import build_game

    cfg = make_cfg()
    game = build_game(cfg, fake=False)
    app = create_app(game, cfg, start_timer=False)
    client = TestClient(TestServer(app))
    await client.start_server()   # create_app's hooks run game.startup()
    try:
        await client.get("/init")
        res = await client.get("/fetch/contents")
        data = await res.json()
        raw = base64.b64decode(data["image"])
        assert raw[:2] == b"\xff\xd8"            # real generated JPEG
        prompt = data["prompt"]
        assert prompt["tokens"] and prompt["masks"]
        res = await client.post(
            "/compute_score",
            json={"inputs": {str(prompt["masks"][0]): "stormy"}})
        scores = await res.json()
        assert "won" in scores
    finally:
        await client.close()   # cleanup hook runs game.shutdown()
