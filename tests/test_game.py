"""Engine integration: full round lifecycle on a fast clock with the fake
backend (SURVEY.md §4 test pyramid tier 4, at time_per_prompt=2s scaled
down further via the injectable store clock)."""

import asyncio
import dataclasses

import pytest

from cassmantle_tpu.config import test_config as _tiny_config
from cassmantle_tpu.engine.content import (
    FakeContentBackend,
    hash_embed,
    hash_similarity,
)
from cassmantle_tpu.engine.game import Game
from cassmantle_tpu.engine.store import MemoryStore


def make_game(time_per_prompt=2.0):
    cfg = _tiny_config()
    cfg = cfg.replace(game=dataclasses.replace(
        cfg.game, time_per_prompt=time_per_prompt,
    ))
    store = MemoryStore()
    backend = FakeContentBackend(image_size=32)
    game = Game(cfg, store, backend, hash_embed, hash_similarity)
    return game, backend


@pytest.mark.asyncio
async def test_startup_creates_round():
    game, backend = make_game()
    await game.startup()
    prompt = await game.rounds.fetch_current_prompt()
    assert prompt["tokens"] and len(prompt["masks"]) == 2
    image = await game.rounds.fetch_current_image()
    assert image.shape == (32, 32, 3)
    story = await game.fetch_story()
    assert story["episode"] == "1" and story["title"]


@pytest.mark.asyncio
async def test_startup_resumes_existing_round():
    game, backend = make_game()
    await game.startup()
    assert backend.calls == 1
    # second worker startup on the same store: no regeneration
    await game.startup()
    assert backend.calls == 1


@pytest.mark.asyncio
async def test_client_session_and_status():
    game, _ = make_game()
    await game.startup()
    assert (await game.client_status(None))["needInitialization"]
    await game.init_client("s1")
    status = await game.client_status("s1")
    assert status == {"won": 0, "needInitialization": False}
    assert await game.sessions.player_count() == 1


@pytest.mark.asyncio
async def test_prompt_json_masks_hidden():
    game, _ = make_game()
    await game.startup()
    await game.init_client("s1")
    prompt = await game.fetch_prompt_json("s1")
    for mask in prompt["masks"]:
        assert prompt["tokens"][mask] == "*"
    assert prompt["correct"] == []
    assert prompt["attempts"] == 0


@pytest.mark.asyncio
async def test_guess_flow_wrong_then_win():
    game, _ = make_game()
    await game.startup()
    await game.init_client("s1")
    current = await game.rounds.fetch_current_prompt()
    masks = current["masks"]
    answers = {str(m): current["tokens"][m] for m in masks}

    wrong = {str(m): "zzzz" for m in masks}
    result = await game.compute_client_scores("s1", wrong)
    assert result["won"] == 0

    result = await game.compute_client_scores("s1", answers)
    assert result["won"] == 1
    status = await game.client_status("s1")
    assert status["won"] == 1
    prompt = await game.fetch_prompt_json("s1")
    assert prompt["masks"] == []  # won -> nothing masked
    assert prompt["attempts"] == 2


@pytest.mark.asyncio
async def test_partial_solve_reveals_one_mask():
    game, _ = make_game()
    await game.startup()
    await game.init_client("s1")
    current = await game.rounds.fetch_current_prompt()
    m0, m1 = current["masks"]
    await game.compute_client_scores(
        "s1", {str(m0): current["tokens"][m0], str(m1): "zzzz"}
    )
    prompt = await game.fetch_prompt_json("s1")
    assert -1 in prompt["masks"]
    assert m0 in prompt["correct"]
    assert prompt["tokens"][m1] == "*"
    # solved token is visible again
    assert prompt["tokens"][m0] == current["tokens"][m0]


@pytest.mark.asyncio
async def test_masked_image_blur_decreases_with_score():
    game, _ = make_game()
    await game.startup()
    await game.init_client("s1")
    blurred = await game.fetch_masked_image("s1")
    current = await game.rounds.fetch_current_prompt()
    answers = {str(m): current["tokens"][m] for m in current["masks"]}
    await game.compute_client_scores("s1", answers)
    clear = await game.fetch_masked_image("s1")
    raw = await game.rounds.fetch_current_image()
    # winning -> zero blur -> identical to stored image
    assert (clear == raw).all()
    assert not (blurred == raw).all()


@pytest.mark.asyncio
async def test_stale_mask_input_ignored():
    game, _ = make_game()
    await game.startup()
    await game.init_client("s1")
    result = await game.compute_client_scores("s1", {"999": "anything"})
    assert result == {"won": 0}


@pytest.mark.asyncio
async def test_round_lifecycle_buffer_promote_reset():
    game, backend = make_game(time_per_prompt=1.0)
    await game.startup()
    await game.init_client("s1")
    current0 = await game.rounds.fetch_current_prompt()
    # win before rollover; rollover must reset the session
    answers = {str(m): current0["tokens"][m] for m in current0["masks"]}
    await game.compute_client_scores("s1", answers)
    assert (await game.client_status("s1"))["won"] == 1

    task = game.start_timer(tick=0.1)
    try:
        deadline = asyncio.get_event_loop().time() + 8.0
        promoted = False
        while asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.1)
            story = await game.fetch_story()
            if int(story.get("episode", 0)) >= 2:
                promoted = True
                break
        assert promoted, "round never promoted"
    finally:
        await game.rounds.stop()
        task.cancel()

    assert backend.calls >= 2  # startup + at least one buffer
    # session reset by rollover
    status = await game.client_status("s1")
    assert status["needInitialization"] or status["won"] == 0
    # clock restarted and reset flag behavior: countdown live again
    assert await game.rounds.remaining() > 0


@pytest.mark.asyncio
async def test_promote_without_buffer_replays_round():
    game, _ = make_game()
    await game.startup()
    before = await game.rounds.fetch_current_prompt()
    await game.rounds.promote_buffer()  # no buffer staged
    after = await game.rounds.fetch_current_prompt()
    assert before == after


@pytest.mark.asyncio
async def test_story_continuation_uses_prompt_seed():
    game, backend = make_game()
    await game.startup()
    seeds_seen = []

    class SpyBackend(FakeContentBackend):
        async def generate(self, seed, is_seed):
            seeds_seen.append((seed, is_seed))
            return await super().generate(seed, is_seed)

    game.rounds.backend = SpyBackend(image_size=32)
    await game.rounds.buffer_contents()
    await game.rounds.promote_buffer()
    assert len(seeds_seen) == 1
    seed, is_seed = seeds_seen[0]
    assert not is_seed  # continues the story, not a fresh seed
    prev = await game.store.hget("prompt", "seed")
    assert prev is not None


@pytest.mark.asyncio
async def test_clock_payload_shape():
    game, _ = make_game()
    await game.startup()
    await game.rounds.start_countdown()
    payload = await game.clock_payload()
    assert set(payload) == {"time", "reset", "conns"}
    assert ":" in payload["time"]


@pytest.mark.asyncio
async def test_masked_image_b64_cache_hit_and_promote_invalidation():
    """The hot-path reveal caches (round image, blur bucket) -> base64:
    same-bucket requests render once; a promotion (new image bytes)
    invalidates the cache."""
    from cassmantle_tpu.utils.logging import metrics

    game, _ = make_game()
    await game.rounds.startup()
    await game.init_client("s1")
    await game.init_client("s2")

    before = dict(metrics.snapshot()["counters"])
    b1 = await game.fetch_masked_image_b64("s1")
    b2 = await game.fetch_masked_image_b64("s2")     # same bucket -> hit
    assert b1 == b2
    after = dict(metrics.snapshot()["counters"])
    assert after.get("game.image_cache_hits", 0) \
        - before.get("game.image_cache_hits", 0) == 1
    assert after.get("game.image_cache_misses", 0) \
        - before.get("game.image_cache_misses", 0) == 1

    # b64 payload decodes back to the round image shape
    import base64

    from cassmantle_tpu.utils.codec import decode_jpeg

    img = decode_jpeg(base64.b64decode(b1))
    assert img.shape[-1] == 3

    # promotion swaps the bytes -> old cache entries must not serve
    await game.rounds.buffer_contents()
    await game.rounds.promote_buffer()
    b3 = await game.fetch_masked_image_b64("s1")
    assert b3 != b1


@pytest.mark.asyncio
async def test_masked_image_b64_bucket_separates_scores():
    """A solved session (score 1 -> radius 0) must NOT be served the
    blurred cache entry of an unsolved one."""
    game, _ = make_game()
    await game.rounds.startup()
    await game.init_client("fresh")
    await game.init_client("winner")
    masks = await game.rounds.current_masks()
    await game.sessions.set_scores(
        "winner", {str(m): 1.0 for m in masks})

    blurred = await game.fetch_masked_image_b64("fresh")
    sharp = await game.fetch_masked_image_b64("winner")
    assert blurred != sharp


def _slow_image_bytes(game, delay_s=0.05):
    """Wrap the round's byte fetch with a real await so a render stays
    in flight long enough for concurrent requests to pile onto it (the
    in-memory store never yields, so without this every coroutine runs
    to completion before the next starts and coalescing is never
    exercised)."""
    orig = game.rounds.fetch_current_image_bytes

    async def slow():
        await asyncio.sleep(delay_s)
        return await orig()

    game.rounds.fetch_current_image_bytes = slow


@pytest.mark.asyncio
async def test_masked_image_b64_single_flight():
    """Concurrent same-bucket misses coalesce to ONE in-flight render
    (the reset stampede case: every client refetches the instant the
    cache was invalidated)."""
    game, _ = make_game()
    await game.rounds.startup()
    for i in range(5):
        await game.init_client(f"c{i}")
    _slow_image_bytes(game)

    renders = 0
    orig = game.blur_fn

    def counting_blur(image, radius):
        nonlocal renders
        renders += 1
        return orig(image, radius)

    game.blur_fn = counting_blur
    results = await asyncio.gather(
        *[game.fetch_masked_image_b64(f"c{i}") for i in range(5)]
    )
    assert len(set(results)) == 1
    assert renders == 1


@pytest.mark.asyncio
async def test_masked_image_render_runs_off_event_loop():
    """The decode+blur+encode of a bucket miss is CPU work that must not
    stall the event loop (the 1 Hz clock pushes ride it) — it runs in a
    worker thread (VERDICT r2 weak #7)."""
    import threading

    game, _ = make_game()
    await game.rounds.startup()
    await game.init_client("c0")

    loop_thread = threading.current_thread()
    render_threads = []
    orig = game.blur_fn

    def recording_blur(image, radius):
        render_threads.append(threading.current_thread())
        return orig(image, radius)

    game.blur_fn = recording_blur
    await game.fetch_masked_image_b64("c0")
    assert render_threads and all(
        t is not loop_thread for t in render_threads)


@pytest.mark.asyncio
async def test_masked_image_b64_waiter_cancellation_isolated():
    """One waiter's cancellation (client disconnect mid-request) must
    not cancel the shared render or fail the other coalesced waiters."""
    game, _ = make_game()
    await game.rounds.startup()
    for i in range(3):
        await game.init_client(f"c{i}")
    _slow_image_bytes(game)

    tasks = [asyncio.ensure_future(game.fetch_masked_image_b64(f"c{i}"))
             for i in range(3)]
    await asyncio.sleep(0.01)        # all three joined the in-flight render
    tasks[0].cancel()
    results = await asyncio.gather(*tasks, return_exceptions=True)
    assert isinstance(results[0], asyncio.CancelledError)
    assert isinstance(results[1], str) and results[1] == results[2]
