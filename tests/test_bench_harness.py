"""Bench suite harness resilience (the round-1 failure mode: a device
tunnel dying mid-suite hangs an in-process entry forever and loses
every number). Entries run in per-entry subprocesses with wall-clock
timeouts; a hung entry becomes a clean error record and the suite
moves on."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def test_suite_survives_hung_entry(tmp_path):
    """With a 3s entry budget, the scorer entry (which needs ~2min on
    CPU) times out — the suite records the timeout as data instead of
    hanging, and exits cleanly because the north star wasn't asked
    for."""
    env = dict(os.environ,
               BENCH_SUITE_ENTRIES="scorer", BENCH_ENTRY_TIMEOUT="3")
    proc = subprocess.run(
        [sys.executable, BENCH, "--suite", "--platform-cpu"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    partial = os.path.join(REPO, "BENCH_SUITE.partial.json")
    try:
        results = json.load(open(partial))
    finally:
        os.path.exists(partial) and os.remove(partial)
    assert "timeout" in results["scorer"]["error"]


def test_unknown_entry_rejected():
    proc = subprocess.run(
        [sys.executable, BENCH, "--entry", "nope", "--platform-cpu"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode != 0
    assert "unknown suite entry" in proc.stderr
