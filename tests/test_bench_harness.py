"""Bench suite harness resilience (the round-1 failure mode: a device
tunnel dying mid-suite hangs an in-process entry forever and loses
every number). Entries run in per-entry subprocesses with wall-clock
timeouts; a hung entry becomes a clean error record and the suite
moves on."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def test_suite_survives_hung_entry(tmp_path):
    """With a 3s entry budget, the scorer entry (which needs ~2min on
    CPU) times out — the suite records the timeout as data instead of
    hanging, and exits cleanly because the north star wasn't asked
    for."""
    suite_path = str(tmp_path / "BENCH_SUITE.json")
    env = dict(os.environ, BENCH_SUITE_ENTRIES="scorer",
               BENCH_ENTRY_TIMEOUT="3", BENCH_SUITE_PATH=suite_path)
    proc = subprocess.run(
        [sys.executable, BENCH, "--suite", "--platform-cpu"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    results = json.load(open(suite_path))
    assert "timeout" in results["scorer"]["error"]
    assert "measured_at" in results["scorer"]


def test_suite_error_never_clobbers_prior_success(tmp_path):
    """Merge semantics (the round 1-3 failure mode: a mid-suite outage
    zeroed whole runs): a fresh ERROR keeps the previously-measured
    success; a fresh success overwrites; and the file is rewritten
    per-entry, not at suite end."""
    suite_path = str(tmp_path / "BENCH_SUITE.json")
    prior = {"scorer": {"metric": "scorer", "value": 3702.4,
                        "unit": "pairs/sec",
                        "measured_at": "2026-07-01T00:00:00Z"}}
    json.dump(prior, open(suite_path, "w"))
    env = dict(os.environ, BENCH_SUITE_ENTRIES="scorer",
               BENCH_ENTRY_TIMEOUT="3", BENCH_SUITE_PATH=suite_path)
    proc = subprocess.run(
        [sys.executable, BENCH, "--suite", "--platform-cpu"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-1000:]
    results = json.load(open(suite_path))
    # the timeout error must NOT have replaced the measured number
    assert results["scorer"]["value"] == 3702.4
    assert "error" not in results["scorer"]
    assert "keeping prior measurement" in proc.stderr


def test_suite_persists_each_entry_as_it_lands(tmp_path, monkeypatch):
    """The suite file must exist with entry 1's result BEFORE entry 2
    runs — verified by having entry 2's (fake) runner read the file."""
    bench = _import_bench()
    suite_path = str(tmp_path / "BENCH_SUITE.json")
    seen_at_entry2 = {}

    def fake_isolated(name, weights_dir, timeout_s, cpu=False):
        if name == "gpt2" and os.path.exists(suite_path):
            seen_at_entry2.update(json.load(open(suite_path)))
        return {"metric": name, "value": 1.0}

    monkeypatch.setattr(bench, "_run_entry_isolated", fake_isolated)
    monkeypatch.setattr(bench, "probe_device", lambda *a, **k: None)
    monkeypatch.setenv("BENCH_SUITE_PATH", suite_path)
    monkeypatch.setenv("BENCH_SUITE_ENTRIES", "scorer,gpt2")
    monkeypatch.setattr(sys, "argv", ["bench.py", "--suite",
                                      "--platform-cpu"])
    bench.main()
    assert seen_at_entry2["scorer"]["value"] == 1.0
    final = json.load(open(suite_path))
    assert set(final) == {"scorer", "gpt2"}


def test_fresh_north_star_failure_exits_nonzero(tmp_path, monkeypatch):
    """When sd15 fails THIS run, the suite must exit non-zero even
    though the file keeps a prior measurement — callers keying on the
    exit code must never mistake a stale number for a fresh run."""
    bench = _import_bench()
    suite_path = str(tmp_path / "BENCH_SUITE.json")
    with open(suite_path, "w") as f:
        json.dump({"sd15": {"metric": "sd15", "value": 1.19,
                            "measured_at": "2026-06-01T00:00:00Z"}}, f)
    monkeypatch.setattr(
        bench, "_run_entry_isolated",
        lambda name, w, t, cpu=False: {"metric": name,
                                       "error": "tunnel died"})
    monkeypatch.setenv("BENCH_SUITE_PATH", suite_path)
    monkeypatch.setenv("BENCH_SUITE_ENTRIES", "sd15")
    monkeypatch.setattr(sys, "argv", ["bench.py", "--suite",
                                      "--platform-cpu"])
    try:
        bench.main()
        raise AssertionError("suite should have exited non-zero")
    except SystemExit as e:
        assert "north-star bench failed" in str(e)
    # ...but the file still holds the prior hardware evidence
    assert json.load(open(suite_path))["sd15"]["value"] == 1.19


def test_north_star_only_runs_fast_path(tmp_path, monkeypatch):
    """--north-star-only runs exactly NORTH_STAR_ENTRIES (sd15 first)
    at 1 timed round unless the caller pinned a rep count — the
    short-tunnel-window fast path."""
    bench = _import_bench()
    suite_path = str(tmp_path / "BENCH_SUITE.json")
    ran = []

    def fake_isolated(name, weights_dir, timeout_s, cpu=False):
        ran.append((name, os.environ.get("BENCH_ROUNDS")))
        return {"metric": name, "value": 2.0}

    monkeypatch.setattr(bench, "_run_entry_isolated", fake_isolated)
    monkeypatch.setattr(bench, "probe_device", lambda *a, **k: None)
    monkeypatch.setenv("BENCH_SUITE_PATH", suite_path)
    monkeypatch.delenv("BENCH_ROUNDS", raising=False)
    monkeypatch.delenv("BENCH_SUITE_ENTRIES", raising=False)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--north-star-only",
                                      "--platform-cpu"])
    bench.main()
    assert [n for n, _ in ran] == list(bench.NORTH_STAR_ENTRIES)
    assert ran[0] == ("sd15", "1")  # children inherit the 1-rep env
    assert set(json.load(open(suite_path))) == set(bench.NORTH_STAR_ENTRIES)


def test_suite_order_is_north_star_first():
    """Tunnels die mid-suite: sd15 and sd15_turbo must be the first two
    entries so a partial run still lands the perf-case numbers."""
    bench = _import_bench()
    assert list(bench.SUITE)[:2] == ["sd15", "sd15_turbo"]


def test_kept_prior_is_annotated_with_fresh_error(tmp_path, monkeypatch):
    """When a fresh error keeps a prior success, the persisted record
    must say this run failed (last_error/last_error_at), and the
    per-entry stderr JSON stream must carry the fresh error — not
    reprint the old success as if re-measured."""
    bench = _import_bench()
    suite_path = str(tmp_path / "BENCH_SUITE.json")
    with open(suite_path, "w") as f:
        json.dump({"scorer": {"metric": "scorer", "value": 3702.4,
                              "measured_at": "2026-07-01T00:00:00Z"}}, f)
    monkeypatch.setattr(
        bench, "_run_entry_isolated",
        lambda name, w, t, cpu=False: {"metric": name,
                                       "error": "tunnel died"})
    monkeypatch.setattr(bench, "probe_device", lambda *a, **k: None)
    monkeypatch.setenv("BENCH_SUITE_PATH", suite_path)
    monkeypatch.setenv("BENCH_SUITE_ENTRIES", "scorer")
    monkeypatch.setattr(sys, "argv", ["bench.py", "--suite",
                                      "--platform-cpu"])
    bench.main()
    rec = json.load(open(suite_path))["scorer"]
    assert rec["value"] == 3702.4          # evidence kept
    assert rec["last_error"] == "tunnel died"
    assert "last_error_at" in rec and "error" not in rec


def test_persist_merges_concurrent_writers(tmp_path, monkeypatch):
    """Two suite runs sharing one BENCH_SUITE.json must not drop each
    other's entries: persist re-reads the file at write time, so an
    entry another run landed mid-flight survives our write."""
    bench = _import_bench()
    suite_path = str(tmp_path / "BENCH_SUITE.json")

    def fake_isolated(name, weights_dir, timeout_s, cpu=False):
        # simulate a concurrent --north-star-only run landing sd15
        # while our run is measuring the scorer
        with open(suite_path, "w") as f:
            json.dump({"sd15": {"metric": "sd15", "value": 1.8}}, f)
        return {"metric": name, "value": 3000.0}

    monkeypatch.setattr(bench, "_run_entry_isolated", fake_isolated)
    monkeypatch.setattr(bench, "probe_device", lambda *a, **k: None)
    monkeypatch.setenv("BENCH_SUITE_PATH", suite_path)
    monkeypatch.setenv("BENCH_SUITE_ENTRIES", "scorer")
    monkeypatch.setattr(sys, "argv", ["bench.py", "--suite",
                                      "--platform-cpu"])
    bench.main()
    final = json.load(open(suite_path))
    assert final["sd15"]["value"] == 1.8       # concurrent entry kept
    assert final["scorer"]["value"] == 3000.0  # ours landed too


class _FakeCompleted:
    def __init__(self, rc, stderr="", stdout=""):
        self.returncode = rc
        self.stderr = stderr
        self.stdout = stdout


def _import_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_probe_polls_until_deadline(monkeypatch):
    """The driver invokes bench.py once per round while tunnel outages
    last hours: the probe must keep retrying until BENCH_PROBE_DEADLINE_S
    (not give up after one attempt), and its failure exit must carry the
    attempt count + window as proof the outage spanned the window."""
    bench = _import_bench()
    calls = []

    def fake_run(cmd, timeout, capture_output, text, **kw):
        calls.append(timeout)
        raise subprocess.TimeoutExpired(cmd, timeout)

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    clock = [0.0]

    def fake_monotonic():
        clock[0] += 40.0  # each attempt "takes" 40s
        return clock[0]

    monkeypatch.setattr(bench.time, "monotonic", fake_monotonic)
    monkeypatch.setenv("BENCH_PROBE_DEADLINE_S", "600")
    try:
        bench.probe_device(attempt_timeout_s=5.0)
        raise AssertionError("probe_device should have exited")
    except SystemExit as e:
        msg = str(e)
    assert len(calls) > 3, "one-shot probe regression: must poll"
    assert "attempts over" in msg and "entire probe window" in msg


def test_probe_returns_on_success(monkeypatch):
    bench = _import_bench()
    attempts = []

    def fake_run(cmd, timeout, capture_output, text, **kw):
        attempts.append(1)
        if len(attempts) < 3:
            raise subprocess.TimeoutExpired(cmd, timeout)
        return _FakeCompleted(0)

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setenv("BENCH_PROBE_DEADLINE_S", "3600")
    bench.probe_device(attempt_timeout_s=5.0)  # no SystemExit
    assert len(attempts) == 3


def test_probe_deterministic_failure_exits_fast(monkeypatch):
    """An import error in the probe child fails fast with a nonzero
    exit; that is a bug, not an outage — it must surface after two
    consecutive fast failures instead of burning the 45 min window."""
    bench = _import_bench()
    calls = []

    def fake_run(cmd, timeout, capture_output, text, **kw):
        calls.append(1)
        return _FakeCompleted(1, stderr="ModuleNotFoundError: nope")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setenv("BENCH_PROBE_DEADLINE_S", "3600")
    try:
        bench.probe_device(attempt_timeout_s=5.0)
        raise AssertionError("probe_device should have exited")
    except SystemExit as e:
        msg = str(e)
    assert len(calls) == 2
    assert "deterministically" in msg and "ModuleNotFoundError" in msg


def test_flash_failure_retries_with_kill_switch(monkeypatch):
    """A child whose stderr carries a Pallas/Mosaic marker gets exactly
    one retry with CASSMANTLE_NO_FLASH_CROSS=1, and the measured result
    is labeled flash_cross_disabled so the suite record says which path
    produced the number (the auto-fallback of commit 75aab8c — its
    trigger path, exercised)."""
    bench = _import_bench()
    calls = []

    def fake_run(cmd, timeout, capture_output, text, env):
        calls.append(env)
        if len(calls) == 1:
            return _FakeCompleted(
                1, stderr="Mosaic lowering failed: bad tile")
        return _FakeCompleted(
            0, stdout=json.dumps({"metric": "sd15", "value": 2.0}) + "\n")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.delenv("CASSMANTLE_NO_FLASH_CROSS", raising=False)
    res = bench._run_entry_isolated("sd15", "weights", timeout_s=300.0)
    assert len(calls) == 2
    assert calls[1]["CASSMANTLE_NO_FLASH_CROSS"] == "1"
    assert res["flash_cross_disabled"] is True
    assert res["value"] == 2.0


def test_unrelated_failure_fails_immediately(monkeypatch):
    """A failure without kernel markers (missing weights, OOM) must
    surface its real diagnostic at once — no second pipeline build."""
    bench = _import_bench()
    calls = []

    def fake_run(cmd, timeout, capture_output, text, env):
        calls.append(1)
        return _FakeCompleted(1, stderr="FileNotFoundError: weights/x")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.delenv("CASSMANTLE_NO_FLASH_CROSS", raising=False)
    res = bench._run_entry_isolated("sd15", "weights", timeout_s=300.0)
    assert len(calls) == 1
    assert "FileNotFoundError" in res["error"]


def test_timeout_never_retries(monkeypatch):
    """A wall-clock timeout is a hang (tunnel death), not a kernel
    rejection — retrying would double the entry budget for nothing."""
    bench = _import_bench()
    calls = []

    def fake_run(cmd, timeout, capture_output, text, env):
        calls.append(1)
        raise subprocess.TimeoutExpired(cmd, timeout,
                                        stderr=b"mosaic in the tail")
    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.delenv("CASSMANTLE_NO_FLASH_CROSS", raising=False)
    res = bench._run_entry_isolated("sd15", "weights", timeout_s=300.0)
    assert len(calls) == 1
    assert "timeout" in res["error"]


def test_no_retry_when_kill_switch_already_set(monkeypatch):
    """With the kill switch already in the environment (a prior entry's
    sticky fallback) a mosaic-marked failure is final: the doomed
    compile must not repeat."""
    bench = _import_bench()
    calls = []

    def fake_run(cmd, timeout, capture_output, text, env):
        calls.append(1)
        return _FakeCompleted(1, stderr="Mosaic lowering failed again")

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setenv("CASSMANTLE_NO_FLASH_CROSS", "1")
    res = bench._run_entry_isolated("sd15", "weights", timeout_s=300.0)
    assert len(calls) == 1
    assert "error" in res


def test_unknown_entry_rejected():
    proc = subprocess.run(
        [sys.executable, BENCH, "--entry", "nope", "--platform-cpu"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode != 0
    assert "unknown suite entry" in proc.stderr
