/* Spellchecker with the same API surface as the reference's vendored
 * typo.js (check / suggest; reference static/typo.js:622,755) but built on
 * the framework's served wordlist (/wordlist) instead of hunspell .aff/.dic
 * parsing. Affix handling is rule-based: plural, past, progressive,
 * agentive, superlative and adverb suffixes reduce to a stem before
 * lookup. Suggestions are edit-distance-1 candidates that pass check(),
 * in generation order (deletion, transposition, insertion, substitution
 * at each position, left to right).
 *
 * KEEP IN LOCKSTEP WITH cassmantle_tpu/utils/spell.py — the Python
 * mirror that tests/test_spell.py drives against the served wordlist
 * (no JS runtime in CI); the suffix-rule sets are compared across the
 * two files by test_spell_rule_parity.
 */

"use strict";

/* KEEP IN LOCKSTEP with cassmantle_tpu/utils/spell.py _PREFIXES */
const PREFIXES = ["un", "re", "dis", "mis", "pre", "non", "over", "under", "out", "semi", "anti"];

class Spell {
  constructor(words) {
    /* insertion order IS the frequency rank (the served wordlist is
     * most-common-first); suggestions sort by it so common words beat
     * obscure ones. */
    this.rank = new Map();
    for (const w of words || []) {
      const lw = String(w).toLowerCase();
      if (!this.rank.has(lw)) this.rank.set(lw, this.rank.size);
    }
    this.words = new Set(this.rank.keys());
    this.alphabet = "abcdefghijklmnopqrstuvwxyz";
  }

  _stems(word) {
    const w = word.toLowerCase();
    const out = [w];
    const add = (s) => { if (s.length >= 2) out.push(s); };
    if (w.endsWith("ies")) add(w.slice(0, -3) + "y");
    if (w.endsWith("es")) add(w.slice(0, -2));
    if (w.endsWith("s")) add(w.slice(0, -1));
    if (w.endsWith("ed")) { add(w.slice(0, -2)); add(w.slice(0, -1)); }
    if (w.endsWith("ing")) { add(w.slice(0, -3)); add(w.slice(0, -3) + "e"); }
    if (w.endsWith("ly")) add(w.slice(0, -2));
    if (w.endsWith("er")) { add(w.slice(0, -2)); add(w.slice(0, -1)); }
    if (w.endsWith("est")) { add(w.slice(0, -3)); add(w.slice(0, -2)); }
    // y-inflections (happier/happiest/happily -> happy)
    if (w.endsWith("ier")) add(w.slice(0, -3) + "y");
    if (w.endsWith("iest")) add(w.slice(0, -4) + "y");
    if (w.endsWith("ily")) add(w.slice(0, -3) + "y");
    // f/fe plurals (wolves -> wolf, knives -> knife)
    if (w.endsWith("ves")) { add(w.slice(0, -3) + "f"); add(w.slice(0, -3) + "fe"); }
    // derivational suffixes (brightness, hopeful, stormless, greenish,
    // movement, drinkable)
    if (w.endsWith("ness")) add(w.slice(0, -4));
    if (w.endsWith("ful")) add(w.slice(0, -3));
    if (w.endsWith("less")) add(w.slice(0, -4));
    if (w.endsWith("ish")) add(w.slice(0, -3));
    if (w.endsWith("ment")) add(w.slice(0, -4));
    if (w.endsWith("able")) { add(w.slice(0, -4)); add(w.slice(0, -4) + "e"); }
    // doubled final consonant before -ed/-ing (stopped -> stop)
    const m = w.match(/^(.+?)([bdgklmnprt])\2(ed|ing)$/);
    if (m) add(m[1] + m[2]);
    // prefix stripping composes with every suffix stem above
    // (unfolded -> folded -> fold); one prefix layer, remainder >= 3
    for (const s of out.slice()) {
      for (const p of PREFIXES) {
        if (s.startsWith(p) && s.length - p.length >= 3) {
          out.push(s.slice(p.length));
        }
      }
    }
    return out;
  }

  check(word) {
    if (!word || !/^[a-zA-Z][a-zA-Z'-]*$/.test(word)) return false;
    for (const s of this._stems(word)) {
      if (this.words.has(s)) return true;
    }
    return false;
  }

  /* Edit-distance-1 candidates that pass check(), ranked by corpus
   * frequency (list position), generation order breaking ties;
   * stem-only matches carry their stem's rank. KEEP IN LOCKSTEP with
   * utils/spell.py. */
  suggest(word, limit) {
    limit = limit || 5;
    const w = String(word).toLowerCase();
    const seen = new Set();
    const out = [];
    /* direct lexicon entries strictly beat stem-only matches: the
     * stemmer accepts constructions like "form"+"est" that must never
     * outrank a real word */
    const candRank = (cand) => {
      if (this.rank.has(cand)) return this.rank.get(cand);
      let best = this.rank.size;
      for (const s of this._stems(cand)) {
        if (this.rank.has(s)) best = Math.min(best, this.rank.get(s));
      }
      return this.rank.size + best;
    };
    const consider = (cand) => {
      if (!seen.has(cand) && cand !== w && this.check(cand)) {
        seen.add(cand);
        out.push(cand);
      }
    };
    for (let i = 0; i <= w.length; i++) {
      const head = w.slice(0, i);
      const tail = w.slice(i);
      if (tail) consider(head + tail.slice(1));             // deletion
      if (tail.length > 1)                                   // transposition
        consider(head + tail[1] + tail[0] + tail.slice(2));
      for (const c of this.alphabet) {
        consider(head + c + tail);                           // insertion
        if (tail) consider(head + c + tail.slice(1));        // substitution
      }
    }
    // stable sort: generation order breaks rank ties
    return out.map((c, i) => [candRank(c), i, c])
      .sort((a, b) => a[0] - b[0] || a[1] - b[1])
      .map((t) => t[2])
      .slice(0, limit);
  }
}

window.Spell = Spell;
