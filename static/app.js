/* CassMantle TPU frontend.
 *
 * Capability parity with the reference client (SURVEY.md §2.2):
 * session bootstrap (/client/status -> /init), 1 Hz websocket clock with
 * reset-triggered refetch, content rendering (base64 image, tokenized
 * prompt with inputs at mask indices, score placeholders, solved tokens),
 * guess submission with client-side validation, win banner, clock blink
 * under 60 s. Guess validation runs the Spell checker (static/spell.js,
 * check/suggest parity with the reference's typo.js) over the served
 * /wordlist, with stopword and shape rules on top.
 */

"use strict";

const $ = (id) => document.getElementById(id);

const state = {
  masks: [],
  scores: {},
  won: false,
  stopwords: new Set(),
  spell: null,
  submitting: false,
};

/* ---------------- session bootstrap ---------------- */

async function ensureSession() {
  const res = await fetch("/client/status", { credentials: "include" });
  const data = await res.json();
  if (data.needInitialization) {
    await fetch("/init", { credentials: "include" });
  } else {
    state.won = !!data.won;
  }
}

async function loadWordlist() {
  try {
    const res = await fetch("/wordlist");
    const data = await res.json();
    state.stopwords = new Set(data.stopwords || []);
    if (window.Spell && data.words && data.words.length) {
      state.spell = new Spell(data.words);
    }
  } catch (e) { /* validation degrades gracefully */ }
}

/* ---------------- clock websocket ---------------- */

function connectClock() {
  const proto = location.protocol === "https:" ? "wss:" : "ws:";
  const ws = new WebSocket(`${proto}//${location.host}/clock`);
  ws.onmessage = (ev) => {
    const data = JSON.parse(ev.data);
    const clock = $("clock");
    clock.textContent = data.time;
    const [mm, ss] = data.time.split(":").map(Number);
    clock.classList.toggle("blink", mm * 60 + ss <= 60);
    $("player-count").textContent = `${data.conns}`;
    if (data.reset) {
      state.won = false;
      $("win-banner").classList.add("hidden");
      $("feedback").textContent = "";
      fetchContents();
    }
  };
  ws.onclose = () => setTimeout(connectClock, 2000);
}

/* ---------------- content rendering ---------------- */

async function fetchContents() {
  const res = await fetch("/fetch/contents", { credentials: "include" });
  const data = await res.json();
  $("round-image").src = `data:image/jpeg;base64,${data.image}`;
  renderStory(data.story);
  renderPrompt(data.prompt);
  $("splash").classList.add("hidden");
  $("game").classList.remove("hidden");
}

function renderStory(story) {
  $("story-title").textContent = story.title || "";
  $("episode").textContent = story.episode ? `episode ${story.episode}` : "";
}

function renderPrompt(prompt) {
  const container = $("prompt");
  container.innerHTML = "";
  state.masks = prompt.masks.filter((m) => m >= 0);
  state.scores = prompt.scores || {};
  $("attempts").textContent = `attempts: ${prompt.attempts ?? 0}`;

  const solved = new Set(prompt.correct || []);
  const maskSet = new Set(state.masks);

  prompt.tokens.forEach((token, idx) => {
    if (maskSet.has(idx)) {
      const box = document.createElement("span");
      box.className = "mask-box";
      const input = document.createElement("input");
      input.type = "text";
      input.maxLength = 24;
      input.dataset.mask = idx;
      input.placeholder = scoreHint(idx);
      input.addEventListener("keydown", (ev) => {
        if (ev.key === "Enter") submitGuesses();
      });
      box.appendChild(input);
      container.appendChild(box);
    } else {
      const span = document.createElement("span");
      span.textContent = token;
      span.className = "token";
      if (solved.has(idx)) span.classList.add("solved");
      container.appendChild(span);
    }
    container.appendChild(document.createTextNode(" "));
  });

  if (state.won || prompt.masks.length === 0) {
    $("win-banner").classList.toggle("hidden", !state.won);
  }
}

function scoreHint(maskIdx) {
  const s = parseFloat(state.scores[String(maskIdx)] || "0");
  if (!s || s <= 0.1) return "guess…";
  return `${Math.round(s * 100)}% close`;
}

/* ---------------- guessing ---------------- */

function validGuess(word) {
  if (!word) return "enter a word";
  if (!/^[a-zA-Z][a-zA-Z'-]*$/.test(word)) return "letters only";
  if (word.length < 2) return "too short";
  if (state.stopwords.has(word.toLowerCase())) return "too common";
  return null;
}

/* Advisory only: answers come from unrestricted LM output, so an absent
 * word must never block submission (the served list is far smaller than
 * the reference's full hunspell dictionary) — it just earns a hint. */
function spellHint(word) {
  if (!state.spell || state.spell.check(word)) return null;
  const hints = state.spell.suggest(word, 3);
  return hints.length
    ? `unusual word — did you mean ${hints.join(", ")}?`
    : null;
}

async function submitGuesses() {
  if (state.submitting || state.won) return;
  const inputs = {};
  let error = null;
  let hint = null;
  document.querySelectorAll("#prompt input").forEach((input) => {
    const word = input.value.trim();
    if (!word) return;
    const problem = validGuess(word);
    if (problem) { error = `"${word}": ${problem}`; return; }
    hint = hint || spellHint(word);
    inputs[input.dataset.mask] = word;
  });
  if (error) { $("feedback").textContent = error; return; }
  if (hint) $("feedback").textContent = hint;
  if (Object.keys(inputs).length === 0) {
    $("feedback").textContent = "type a guess first";
    return;
  }

  state.submitting = true;
  $("submit").disabled = true;
  try {
    const res = await fetch("/compute_score", {
      method: "POST",
      credentials: "include",
      headers: { "Content-Type": "application/json" },
      body: JSON.stringify({ inputs }),
    });
    const scores = await res.json();
    state.won = scores.won === 1;
    if (state.won) {
      $("win-banner").classList.remove("hidden");
      $("feedback").textContent = "";
    } else {
      const best = Math.max(
        ...Object.entries(scores)
          .filter(([k]) => k !== "won")
          .map(([, v]) => parseFloat(v))
      );
      $("feedback").textContent =
        best > 0.1 ? `${Math.round(best * 100)}% close — keep going`
                   : "cold — try different words";
    }
    await fetchContents();
  } finally {
    state.submitting = false;
    $("submit").disabled = false;
  }
}

/* ---------------- consent ---------------- */

function setupConsent() {
  if (localStorage.getItem("cassmantle-consent")) return;
  $("consent").classList.remove("hidden");
  $("consent-ok").addEventListener("click", () => {
    localStorage.setItem("cassmantle-consent", "1");
    $("consent").classList.add("hidden");
  });
}

/* ---------------- boot ---------------- */

async function init() {
  setupConsent();
  $("submit").addEventListener("click", submitGuesses);
  try {
    await ensureSession();
    await loadWordlist();
    await fetchContents();
    connectClock();
  } catch (e) {
    $("splash-status").textContent = "Server unavailable — retrying…";
    setTimeout(init, 3000);
  }
}

init();
