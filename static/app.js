/* CassMantle TPU frontend.
 *
 * Capability parity with the reference client (SURVEY.md §2.2):
 * session bootstrap (/client/status -> /init), 1 Hz websocket clock with
 * reset-triggered refetch, content rendering (base64 image, tokenized
 * prompt with inputs at mask indices, score placeholders, solved tokens),
 * guess submission with client-side validation, win banner, clock blink
 * under 60 s. Guess validation runs the Spell checker (static/spell.js,
 * check/suggest parity with the reference's typo.js) over the served
 * /wordlist, with stopword and shape rules on top.
 */

"use strict";

const $ = (id) => document.getElementById(id);

const state = {
  masks: [],
  scores: {},
  won: false,
  stopwords: new Set(),
  spell: null,
  submitting: false,
  confirmed: new Set(),  // words already shown the spellcheck hold
};

/* ---------------- session bootstrap ---------------- */

async function ensureSession() {
  const res = await fetch("/client/status", { credentials: "include" });
  const data = await res.json();
  if (data.needInitialization) {
    await fetch("/init", { credentials: "include" });
  } else {
    state.won = !!data.won;
  }
}

async function loadWordlist() {
  try {
    const res = await fetch("/wordlist");
    const data = await res.json();
    state.stopwords = new Set(data.stopwords || []);
    if (window.Spell && data.words && data.words.length) {
      state.spell = new Spell(data.words);
    }
  } catch (e) { /* validation degrades gracefully */ }
}

/* ---------------- clock websocket ---------------- */

function connectClock() {
  const proto = location.protocol === "https:" ? "wss:" : "ws:";
  const ws = new WebSocket(`${proto}//${location.host}/clock`);
  ws.onmessage = (ev) => {
    const data = JSON.parse(ev.data);
    const clock = $("clock");
    clock.textContent = data.time;
    const [mm, ss] = data.time.split(":").map(Number);
    clock.classList.toggle("blink", mm * 60 + ss <= 60);
    $("player-count").textContent = `${data.conns}`;
    if (data.reset) {
      state.won = false;
      state.confirmed.clear();  // new round, fresh spellcheck holds
      $("win-banner").classList.add("hidden");
      $("feedback").textContent = "";
      fetchContents();
    }
  };
  ws.onclose = () => setTimeout(connectClock, 2000);
}

/* ---------------- content rendering ---------------- */

async function fetchContents() {
  const res = await fetch("/fetch/contents", { credentials: "include" });
  const data = await res.json();
  $("round-image").src = `data:image/jpeg;base64,${data.image}`;
  renderStory(data.story);
  renderPrompt(data.prompt);
  $("splash").classList.add("hidden");
  $("game").classList.remove("hidden");
}

function renderStory(story) {
  $("story-title").textContent = story.title || "";
  $("episode").textContent = story.episode ? `episode ${story.episode}` : "";
}

function renderPrompt(prompt) {
  const container = $("prompt");
  container.innerHTML = "";
  state.masks = prompt.masks.filter((m) => m >= 0);
  state.scores = prompt.scores || {};
  $("attempts").textContent = `attempts: ${prompt.attempts ?? 0}`;

  const solved = new Set(prompt.correct || []);
  const maskSet = new Set(state.masks);

  prompt.tokens.forEach((token, idx) => {
    if (maskSet.has(idx)) {
      const box = document.createElement("span");
      box.className = "mask-box";
      const input = document.createElement("input");
      input.type = "text";
      input.maxLength = 24;
      input.dataset.mask = idx;
      input.placeholder = scoreHint(idx);
      input.addEventListener("keydown", (ev) => {
        if (ev.key === "Enter") submitGuesses();
      });
      box.appendChild(input);
      container.appendChild(box);
    } else {
      const span = document.createElement("span");
      span.textContent = token;
      span.className = "token";
      if (solved.has(idx)) span.classList.add("solved");
      container.appendChild(span);
    }
    container.appendChild(document.createTextNode(" "));
  });

  if (state.won || prompt.masks.length === 0) {
    $("win-banner").classList.toggle("hidden", !state.won);
  }
}

function scoreHint(maskIdx) {
  const s = parseFloat(state.scores[String(maskIdx)] || "0");
  if (!s || s <= 0.1) return "guess…";
  return `${Math.round(s * 100)}% close`;
}

/* ---------------- guessing ---------------- */

function validGuess(word) {
  if (!word) return "enter a word";
  if (!/^[a-zA-Z][a-zA-Z'-]*$/.test(word)) return "letters only";
  if (word.length < 2) return "too short";
  if (state.stopwords.has(word.toLowerCase())) return "too common";
  return null;
}

/* Blocking with a confirm escape hatch. The reference hard-rejects
 * misspelled guesses (its script.js:435-440), and at ~38k served words
 * this lexicon is big enough to do the same — but answers come from
 * unrestricted LM output, so a hard block could make a round
 * unwinnable. First submission of a flagged word is held back with
 * suggestions; submitting the SAME word again sends it anyway. */
function spellHint(word) {
  if (!state.spell || state.spell.check(word)) return null;
  const hints = state.spell.suggest(word, 3);
  return hints.length
    ? `unusual word — did you mean ${hints.join(", ")}? (submit again to send anyway)`
    : `unusual word — submit again to send anyway`;
}

async function submitGuesses() {
  if (state.submitting || state.won) return;
  const inputs = {};
  let error = null;
  const flagged = [];  // [{word, hint}] for unrecognized guesses
  document.querySelectorAll("#prompt input").forEach((input) => {
    const word = input.value.trim();
    if (!word) return;
    const problem = validGuess(word);
    if (problem) { error = `"${word}": ${problem}`; return; }
    const h = spellHint(word);
    if (h) flagged.push({ word: word.toLowerCase(), hint: h });
    inputs[input.dataset.mask] = word;
  });
  if (error) { $("feedback").textContent = error; return; }
  if (Object.keys(inputs).length === 0) {
    $("feedback").textContent = "type a guess first";
    return;
  }
  // per-word hold: block only words not yet shown the hold this round;
  // a word the player already saw held goes through on any later submit
  const fresh = flagged.filter((f) => !state.confirmed.has(f.word));
  if (fresh.length) {
    // hold ONLY the word whose hint is displayed: confirming the whole
    // batch here would let the other flagged words sail through the
    // next submit without the player ever seeing their suggestions
    state.confirmed.add(fresh[0].word);
    $("feedback").textContent = fresh[0].hint;
    return;
  }

  state.submitting = true;
  $("submit").disabled = true;
  try {
    const res = await fetch("/compute_score", {
      method: "POST",
      credentials: "include",
      headers: { "Content-Type": "application/json" },
      body: JSON.stringify({ inputs }),
    });
    const scores = await res.json();
    state.won = scores.won === 1;
    if (state.won) {
      $("win-banner").classList.remove("hidden");
      $("feedback").textContent = "";
    } else {
      const best = Math.max(
        ...Object.entries(scores)
          .filter(([k]) => k !== "won")
          .map(([, v]) => parseFloat(v))
      );
      $("feedback").textContent =
        best > 0.1 ? `${Math.round(best * 100)}% close — keep going`
                   : "cold — try different words";
    }
    await fetchContents();
  } finally {
    state.submitting = false;
    $("submit").disabled = false;
  }
}

/* ---------------- consent ---------------- */

function setupConsent() {
  if (!localStorage.getItem("cassmantle-consent")) {
    $("consent").classList.remove("hidden");
    $("consent-ok").addEventListener("click", () => {
      localStorage.setItem("cassmantle-consent", "1");
      $("consent").classList.add("hidden");
    });
  }
  setupPrivacyModal();
}

/* Privacy-policy modal: opened from the consent notice link; closes on
 * the button, a backdrop click, or Escape (reference surface parity —
 * its page ships a policy modal wired to a link). */
function setupPrivacyModal() {
  const modal = $("privacy-modal");
  const open = (e) => { e.preventDefault(); modal.classList.remove("hidden"); };
  const close = () => modal.classList.add("hidden");
  document.querySelectorAll(".privacy-link").forEach(
    (a) => a.addEventListener("click", open));
  $("privacy-close").addEventListener("click", close);
  modal.addEventListener("click", (e) => { if (e.target === modal) close(); });
  document.addEventListener("keydown", (e) => {
    if (e.key === "Escape" && !modal.classList.contains("hidden")) close();
  });
}

/* ---------------- boot ---------------- */

async function init() {
  setupConsent();
  $("submit").addEventListener("click", submitGuesses);
  try {
    await ensureSession();
    await loadWordlist();
    await fetchContents();
    connectClock();
  } catch (e) {
    $("splash-status").textContent = "Server unavailable — retrying…";
    setTimeout(init, 3000);
  }
}

init();
